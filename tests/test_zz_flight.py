"""Threshold flight recorder (ISSUE 10): quorum-margin math,
contribution bitmaps, DKG phase timelines, the /debug/flight surface
and the recorder's bounds/hygiene.

Late-alphabet filename per the tier-1 chunking convention (ROADMAP
operational constraint). Everything here is host-only crypto — no
device graphs, no fresh XLA compiles.
"""

import asyncio
import json
import threading

import aiohttp
import pytest
from aiohttp import web
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.dkg import DKGConfig, DKGProtocol, LocalBoard
from drand_tpu.http_server.debug import add_trace_routes
from drand_tpu.obs.flight import FLIGHT, FlightRecorder
from drand_tpu.obs.state import reset_observability
from drand_tpu.testing.harness import BeaconTestNetwork
from drand_tpu.utils.clock import FakeClock

PERIOD, GENESIS = 10, 1000


def _boundary(rnd):
    return GENESIS + (rnd - 1) * PERIOD


def _feed(f, rnd, index, offset, verdict="valid", source="grpc",
          n=5, t=3):
    f.note_partial(rnd, index=index, source=source, verdict=verdict,
                   now=_boundary(rnd) + offset, period=PERIOD,
                   genesis=GENESIS, n=n, threshold=t)


# ---------------------------------------------------------------------------
# quorum-margin math against a scripted partial schedule
# ---------------------------------------------------------------------------

def test_quorum_margin_scripted_schedule():
    """t=3-of-5, partials at +1.0/+2.5/+4.0/+7.0: quorum is the THIRD
    valid arrival (+4.0), margin = period - 4.0 = 6.0; the late peer
    (+7.0 > period/2) is flagged late but does not move the quorum."""
    f = FlightRecorder()
    q0 = _sample_count(metrics.GROUP_REGISTRY,
                       "beacon_quorum_margin_seconds")
    for idx, off in ((0, 1.0), (1, 2.5), (4, 4.0)):
        _feed(f, 7, idx, off)
    f.note_quorum(7, have=3, threshold=3, now=_boundary(7) + 4.0,
                  period=PERIOD, genesis=GENESIS, n=5)
    _feed(f, 7, 2, 7.0)  # straggler, after quorum
    rec = f.rounds(1)[0]
    assert rec["round"] == 7
    assert rec["quorum_offset_s"] == pytest.approx(4.0)
    assert rec["margin_s"] == pytest.approx(PERIOD - 4.0)
    # first quorum wins: a re-aggregation attempt never re-times
    f.note_quorum(7, have=4, threshold=3, now=_boundary(7) + 9.0,
                  period=PERIOD, genesis=GENESIS)
    assert f.rounds(1)[0]["margin_s"] == pytest.approx(6.0)
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_quorum_margin_seconds") == q0 + 1
    # per-peer lateness: only the +7.0 arrival crossed period/2
    assert f.peers()["2"]["late"] == 1
    assert f.peers()["0"]["late"] == 0

    # a dying group: quorum after the whole period -> NEGATIVE margin.
    # note_quorum returns True only on the FIRST quorum (the recover
    # milestone gate in chain_store rides this).
    _feed(f, 8, 0, 11.0)
    _feed(f, 8, 1, 11.5)
    _feed(f, 8, 2, 12.0)
    assert f.note_quorum(8, have=3, threshold=3, now=_boundary(8) + 12.0,
                         period=PERIOD, genesis=GENESIS, n=5) is True
    assert f.note_quorum(8, have=4, threshold=3, now=_boundary(8) + 13.0,
                         period=PERIOD, genesis=GENESIS) is False
    assert f.rounds(1)[0]["margin_s"] == pytest.approx(-2.0)


def test_valid_replay_deduped_per_round_and_index():
    """A replayed copy of an already-recorded valid partial records as
    'duplicate': the peer's contributed counter, the arrival histogram
    and the lateness flag never re-count (replays must not own the
    per-peer rates)."""
    f = FlightRecorder()
    a0 = _sample_count(metrics.GROUP_REGISTRY,
                       "beacon_partial_arrival_seconds", source="grpc")
    _feed(f, 5, 1, 1.0)
    _feed(f, 5, 1, 7.0)  # replay, late offset — must not count as late
    rec = f.rounds(1)[0]
    assert [ev["verdict"] for ev in rec["events"]] == ["valid",
                                                       "duplicate"]
    assert f.peers()["1"] == {"contributed": 1, "late": 0, "invalid": 0}
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_partial_arrival_seconds",
                         source="grpc") == a0 + 1
    assert rec["bitmap"][1] == "#"

    # the dedup/bitmap authority survives an event-list flood: a
    # byzantine member fills the capped list with invalids BEFORE an
    # honest partial lands — the honest contribution still counts
    # exactly once (replays stay duplicates) and the bitmap still
    # shows it, even though its event was dropped
    f2 = FlightRecorder(max_events=8)
    _feed(f2, 9, 0, 0.5)
    for _ in range(10):
        _feed(f2, 9, 4, 0.6, verdict="invalid")
    _feed(f2, 9, 1, 1.0)         # honest, lands past the cap
    _feed(f2, 9, 1, 1.5)         # replay of it
    rec = f2.rounds(1)[0]
    assert rec["dropped"] > 0 and len(rec["events"]) == 8
    assert rec["contrib"] == {"0": 0.5, "1": 1.0}
    assert rec["bitmap"] == "##..!"
    assert f2.peers()["1"] == {"contributed": 1, "late": 0, "invalid": 0}


# ---------------------------------------------------------------------------
# contribution bitmap: dead + byzantine node
# ---------------------------------------------------------------------------

def test_contribution_bitmap_dead_and_byzantine():
    """5 nodes: 0/1 on time, 2 late, 3 dead (nothing), 4 byzantine
    (only invalid partials) -> bitmap '##~.!'; the store milestone sets
    the contribution gap to 2 (dead + byzantine)."""
    f = FlightRecorder()
    _feed(f, 3, 0, 0.5)
    _feed(f, 3, 1, 1.0)
    _feed(f, 3, 2, 6.0)            # late: > period/2
    _feed(f, 3, 4, 1.2, verdict="invalid")
    rec = f.rounds(1)[0]
    assert rec["bitmap"] == "##~.!"
    f.note_milestone(3, "store", now=_boundary(3) + 7.0, period=PERIOD,
                     genesis=GENESIS)
    assert metrics.CONTRIBUTION_GAP._value.get() == 2
    assert [m["name"] for m in f.rounds(1)[0]["milestones"]] == ["store"]
    # peer counters: invalid attributed to 4, contributions to 0/1/2
    peers = f.peers()
    assert peers["4"] == {"contributed": 0, "late": 0, "invalid": 1}
    assert peers["2"] == {"contributed": 1, "late": 1, "invalid": 0}


def test_rejects_never_create_ring_entries():
    """DoS posture: stale/future/invalid events for rounds the recorder
    has never seen valid traffic for must NOT create ring entries (a
    garbage flood across round numbers cannot evict live records), and
    window rejects never frame a peer's invalid counter."""
    f = FlightRecorder(max_rounds=4)
    for rnd in range(100, 140):
        _feed(f, rnd, 1, 0.1, verdict="future")
        _feed(f, rnd, 1, 0.1, verdict="stale")
    assert f.rounds(10) == []
    assert f.peers().get("1", {}).get("invalid", 0) == 0
    # invalid DOES count against the claimed index, but still creates
    # no ring entry on its own
    _feed(f, 200, 2, 0.1, verdict="invalid")
    assert f.rounds(10) == []
    assert f.peers()["2"]["invalid"] == 1
    # an index the group cannot hold is never attributed: 2^16 garbage
    # prefixes must not bloat the peers table or the metric cardinality
    _feed(f, 200, 999, 0.1, verdict="invalid")
    _feed(f, 200, -3, 0.1, verdict="invalid")
    assert "999" not in f.peers() and "-3" not in f.peers()
    # ...and appends to a round that EXISTS (valid traffic seen)
    _feed(f, 300, 0, 0.2)
    _feed(f, 300, 2, 0.3, verdict="invalid")
    assert len(f.rounds(1)[0]["events"]) == 2


def test_ring_and_event_bounds_and_reset_hammer():
    """max_rounds FIFO eviction, max_events overflow -> dropped, and
    reset() racing concurrent note_* without KeyError/corruption."""
    f = FlightRecorder(max_rounds=8, max_events=16)
    for rnd in range(1, 30):
        _feed(f, rnd, 0, 0.1)
    recs = f.rounds(100)
    assert len(recs) == 8
    assert recs[0]["round"] == 29 and recs[-1]["round"] == 22
    for i in range(40):
        _feed(f, 29, i % 5, 0.2)
    top = f.rounds(1)[0]
    assert len(top["events"]) == 16
    assert top["dropped"] > 0

    stop = threading.Event()
    errors = []

    def hammer():
        rnd = 0
        while not stop.is_set():
            rnd += 1
            try:
                _feed(f, rnd % 50, rnd % 5, 0.1)
                f.note_quorum(rnd % 50, have=3, threshold=3,
                              now=_boundary(rnd % 50) + 1, period=PERIOD,
                              genesis=GENESIS)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
                return

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for _ in range(200):
        f.reset()
        f.rounds(8)
        f.peers()
    stop.set()
    for t in threads:
        t.join()
    assert not errors


# ---------------------------------------------------------------------------
# DKG phase timeline on the 5-node fixture (one crashed dealer)
# ---------------------------------------------------------------------------

def _make_dkg_nodes(n):
    from drand_tpu.key.keys import Node, new_key_pair

    pairs = [new_key_pair(f"flight-dkg-{i}.test:9{i:03d}",
                          seed=b"flight-dkg%d" % i) for i in range(n)]
    nodes = [Node(identity=p.public, index=i) for i, p in enumerate(pairs)]
    return pairs, nodes


@pytest.mark.asyncio
async def test_dkg_phase_timeline_with_crashed_dealer():
    """The 5-node DKG fixture with node 4 never running: the flight
    timeline shows deal-phase arrivals from exactly dealers 0-3, a
    deal phase that lasted the full 10 s timeout (the crash is VISIBLE
    as the stall), QUAL [0,1,2,3], and dkg_phase_seconds samples."""
    reset_observability()
    n, t = 5, 3
    pairs, nodes = _make_dkg_nodes(n)
    clock = FakeClock()
    t0 = clock.now()
    boards = LocalBoard.make_group(n)
    configs = [DKGConfig(longterm=pairs[i], nonce=b"flight-nonce",
                         new_nodes=nodes, threshold=t, clock=clock,
                         phase_timeout=10, seed=b"flight-crashed")
               for i in range(n - 1)]
    d0 = _sample_count(metrics.GROUP_REGISTRY, "dkg_phase_seconds",
                       phase="deal")

    async def drive_clock():
        for _ in range(8):
            await clock.advance(10)

    results_task = asyncio.gather(*(DKGProtocol(c, b).run()
                                    for c, b in zip(configs, boards[:n - 1])))
    await asyncio.gather(results_task, drive_clock())
    results = results_task.result()
    sessions = FLIGHT.dkg.sessions()
    assert len(sessions) == n - 1
    for s in sessions:
        assert s["done"] and s["error"] is None
        assert s["mode"] == "dkg"
        assert s["qual"] == [0, 1, 2, 3]
        assert s["n_dealers"] == n and s["threshold"] == t
        # dealers 0-3 dealt; the crashed dealer 4 is ABSENT
        assert sorted(s["bundles"]["deal"]) == ["0", "1", "2", "3"]
        assert sorted(s["bundles"]["response"]) == ["0", "1", "2", "3"]
        # every live receiver complained about the silent dealer, so a
        # justification phase ran — and dealer 4 never justified
        assert s["bundles"]["justification"] == {}
        assert s["complaints"] == {"4": [0, 1, 2, 3]}
        phases = [p["phase"] for p in s["phases"]]
        assert phases == ["deal", "response", "justification", "finish"]
        deal = s["phases"][0]
        # fast-sync could not fire (4 of 5 expected): the deal phase
        # ran its whole 10 s phaser window on the fake clock
        assert deal["end_s"] - deal["start_s"] == pytest.approx(10.0)
        for p in s["phases"]:
            assert p["end_s"] is not None
    assert _sample_count(metrics.GROUP_REGISTRY, "dkg_phase_seconds",
                         phase="deal") >= d0 + (n - 1)

    # secret hygiene: the recorder state never saw any node's share.
    # Partials are public; shares are NOT — serialize everything the
    # recorder retains and assert no pri_share value (decimal or hex)
    # appears, nor the field name itself.
    blob = json.dumps({"rounds": FLIGHT.rounds(FLIGHT.max_rounds),
                       "peers": FLIGHT.peers(),
                       "dkg": FLIGHT.dkg.sessions()})
    assert "pri_share" not in blob
    for r in results:
        if r.pri_share is None:
            continue
        assert str(r.pri_share.value) not in blob
        assert format(r.pri_share.value, "x") not in blob


# ---------------------------------------------------------------------------
# live network: per-partial telemetry + dead-peer degradation
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_network_flight_records_and_dead_peer_degrades():
    """A 3-node t=2 network produces rounds with full '###' bitmaps and
    positive quorum margins; killing node 2 degrades the bitmap to
    '##.' and sets the contribution gap — all while rounds still
    aggregate (the early-warning half of the acceptance demo)."""
    reset_observability()
    net = BeaconTestNetwork(n=3, t=2, period=5)
    await net.start_all()
    await net.advance_to_genesis()
    for r in range(1, 3):
        await net.clock.advance(net.group.period)
        for i in range(3):
            await net.wait_round(i, r)
    healthy = {rec["round"]: rec for rec in FLIGHT.rounds(16)}
    assert healthy, "no flight records after live rounds"
    # only the rounds we waited for — the NEXT round's partials may
    # already be recorded while its aggregation is still in flight
    full = [rec for rec in healthy.values()
            if rec["bitmap"] == "###" and rec["round"] <= 2]
    assert full, f"no full-participation bitmap: {healthy}"
    for rec in full:
        assert rec["margin_s"] is not None and rec["margin_s"] > 0
        names = [m["name"] for m in rec["milestones"]]
        assert names[0] == "quorum"
        assert "recover" in names and "store" in names
        sources = {ev["source"] for ev in rec["events"]}
        assert "self" in sources and "grpc" in sources

    # ---- kill node 2: quorum survives (t=2), its column goes dark ----
    # anchor past the highest round the recorder has already seen —
    # the next round's partials (node 2's included) may be in flight
    seen = max(rec["round"] for rec in FLIGHT.rounds(16))
    net.nodes[2].handler.stop()
    for r in range(seen + 1, seen + 3):
        await net.clock.advance(net.group.period)
        for i in range(2):
            await net.wait_round(i, r)
    degraded = [rec for rec in FLIGHT.rounds(16)
                if seen < rec["round"] <= seen + 2 and rec["bitmap"]]
    assert degraded
    for rec in degraded:
        assert rec["bitmap"].endswith("."), rec["bitmap"]
        assert rec["margin_s"] is not None
    assert metrics.CONTRIBUTION_GAP._value.get() == 1
    # arrivals landed under both ingress sources, none under gossip
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_partial_arrival_seconds",
                         source="self") > 0
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_partial_arrival_seconds",
                         source="grpc") > 0
    net.stop_all()


# ---------------------------------------------------------------------------
# /debug/flight routes + util flight rendering
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_debug_flight_routes_and_cli_rendering(capsys):
    reset_observability()
    _feed(FLIGHT, 41, 0, 0.5)
    _feed(FLIGHT, 41, 1, 6.0)
    _feed(FLIGHT, 41, 3, 0.7, verdict="invalid")
    FLIGHT.note_quorum(41, have=2, threshold=2, now=_boundary(41) + 6.0,
                       period=PERIOD, genesis=GENESIS, n=4)
    sid = FLIGHT.dkg.begin(b"route-nonce", mode="dkg", n_dealers=3,
                           n_receivers=3, threshold=2, now=100.0)
    FLIGHT.dkg.note_phase(sid, "deal", now=100.0)
    FLIGHT.dkg.note_bundle(sid, "deal", 0, now=100.5)
    FLIGHT.dkg.note_phase(sid, "response", now=101.0)
    FLIGHT.dkg.finish(sid, now=102.0, qual=[0, 1, 2])

    app = web.Application()
    add_trace_routes(app)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/debug/flight/"
                             f"rounds?n=4") as r:
                assert r.status == 200
                rounds_payload = await r.json()
            async with s.get(f"http://127.0.0.1:{port}/debug/flight/"
                             f"rounds?n=1e9") as r:
                assert r.status == 400
            async with s.get(f"http://127.0.0.1:{port}/debug/flight/"
                             f"dkg") as r:
                assert r.status == 200
                dkg_payload = await r.json()
    finally:
        await runner.cleanup()

    rec = rounds_payload["rounds"][0]
    assert rec["round"] == 41 and rec["bitmap"] == "#~.!"
    assert rounds_payload["peers"]["3"]["invalid"] == 1
    ses = dkg_payload["sessions"][0]
    assert ses["qual"] == [0, 1, 2]
    assert [p["phase"] for p in ses["phases"]] == ["deal", "response"]
    assert ses["phases"][1]["end_s"] == pytest.approx(2.0)

    # the util flight renderers consume exactly these payloads
    from drand_tpu.cli.__main__ import (_print_flight_dkg,
                                        _print_flight_matrix)

    _print_flight_matrix(rounds_payload)
    out = capsys.readouterr().out
    assert "# ~ . !" in out          # the matrix row for round 41
    assert "41" in out and "2/2" in out
    assert "invalid" in out          # peers table header
    _print_flight_dkg(dkg_payload)
    out = capsys.readouterr().out
    assert "QUAL: [0, 1, 2]" in out
    assert "deal" in out and "0@+0.500s" in out


# ---------------------------------------------------------------------------
# OTLP satellites: node resource attrs + spool shipping
# ---------------------------------------------------------------------------

def test_otlp_node_attrs_gated(monkeypatch):
    """drand.node.address rides exported spans ONLY under
    DRAND_TPU_OTLP_NODE_ATTRS=1 (privacy default-off)."""
    from drand_tpu.obs import export as obs_export
    from drand_tpu.obs import trace

    obs_export.set_node_address("node-a.test:8001")
    tr = trace.Tracer()
    with tr.activate(round_no=5, chain=b"attr-chain"):
        with tr.span("partial"):
            pass
    rec = tr.get_trace(trace.round_trace_id(5, b"attr-chain"))
    exp = obs_export.OTLPExporter(spool_path="/dev/null")

    monkeypatch.delenv("DRAND_TPU_OTLP_NODE_ATTRS", raising=False)
    attrs = {a["key"] for a in
             exp._payload(rec)["resourceSpans"][0]["resource"]["attributes"]}
    assert "drand.node.address" not in attrs

    monkeypatch.setenv("DRAND_TPU_OTLP_NODE_ATTRS", "1")
    res = exp._payload(rec)["resourceSpans"][0]["resource"]["attributes"]
    by_key = {a["key"]: a["value"] for a in res}
    assert by_key["drand.node.address"]["stringValue"] == "node-a.test:8001"


@pytest.mark.asyncio
async def test_ship_spool_batches_retries_and_truncates(tmp_path):
    """ship_spool re-POSTs the spooled ring in merged batches, retries
    a transiently failing collector with backoff, truncates both ring
    files on success, and leaves the spool intact on permanent
    failure."""
    from drand_tpu.obs import export as obs_export
    from drand_tpu.obs import trace

    spool = str(tmp_path / "ship.ndjson")
    exp = obs_export.OTLPExporter(spool_path=spool)
    tr = trace.Tracer()
    for rnd in range(1, 8):
        with tr.activate(round_no=rnd, chain=b"ship-chain"):
            with tr.span("store", rnd=rnd):
                pass
        assert exp.export_round_sync(
            tr.get_trace(trace.round_trace_id(rnd, b"ship-chain"))) == "spool"

    # a daemon killed mid-append leaves a truncated line: the shipper
    # (and any read_spool consumer) must skip it, not crash-loop
    with open(spool, "a", encoding="utf-8") as fh:
        fh.write('{"resourceSpans": [{"trunc')
    assert len(obs_export.read_spool(spool)) == 7

    posts, fail_first = [], [2]  # fail the first two POSTs

    async def collector(request):
        if fail_first[0] > 0:
            fail_first[0] -= 1
            return web.Response(status=503)
        posts.append(await request.json())
        return web.json_response({})

    app = web.Application()
    app.add_routes([web.post("/v1/traces", collector)])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        out = await obs_export.ship_spool(
            spool, f"http://127.0.0.1:{port}", batch_size=3,
            backoff=0.01)
        assert out == {"shipped": 7, "batches": 3, "ok": True}
        # batches merged resourceSpans; every spooled round arrived
        spans = [sp for doc in posts for rs in doc["resourceSpans"]
                 for ss in rs["scopeSpans"] for sp in ss["spans"]]
        assert len(spans) == 7
        # truncated on success; a re-ship is a no-op
        assert obs_export.read_spool(spool) == []
        out = await obs_export.ship_spool(spool,
                                          f"http://127.0.0.1:{port}")
        assert out == {"shipped": 0, "batches": 0, "ok": True}

        # permanent failure keeps the spool for the next cycle
        for rnd in range(20, 23):
            with tr.activate(round_no=rnd, chain=b"ship-chain"):
                with tr.span("store"):
                    pass
            exp.export_round_sync(
                tr.get_trace(trace.round_trace_id(rnd, b"ship-chain")))
        fail_first[0] = 10 ** 6
        out = await obs_export.ship_spool(
            spool, f"http://127.0.0.1:{port}", attempts=2, backoff=0.01)
        assert out["ok"] is False
        assert len(obs_export.read_spool(spool)) == 3
    finally:
        await runner.cleanup()
