"""Golden tests: batch-last hash-to-G2 + decompression (ops/bl_h2c.py)
vs the host RFC 9380 pipeline and PointG2.from_bytes."""

import pytest

pytestmark = pytest.mark.device

import random

import numpy as np
import jax.numpy as jnp

from drand_tpu.crypto import hash_to_curve as hh
from drand_tpu.crypto.curves import PointG2
from drand_tpu.ops import bl_curve as blc
from drand_tpu.ops import bl_h2c as blh
from drand_tpu.ops import h2c as xh2c
from drand_tpu.ops.pallas_pairing import value_bit_getter

rng = random.Random(0x2BC4)
B = 4


def getters():
    return (value_bit_getter(jnp.asarray(blh.SQRT_BITS)),
            value_bit_getter(jnp.asarray(blc.X_BITS)))


def test_canonicalize_sgn0():
    from drand_tpu.crypto.fields import P, Fp2
    from drand_tpu.ops import bl

    xs = [rng.randrange(P) for _ in range(B)]
    a = jnp.asarray(bl.pack_fp(xs))
    # a + a - a ... keep non-canonical representation, canonicalize back
    noisy = bl.add(bl.add(a, a), bl.neg(a))
    canon = np.asarray(blh.canonicalize(blh.from_mont(noisy)))
    import drand_tpu.ops.limb as limb

    got = [limb.limbs_to_int(canon[..., j]) for j in range(B)]
    assert got == xs
    # sgn0 parity vs host
    f2s = [Fp2(rng.randrange(P), rng.randrange(P)) for _ in range(B)]
    packed = np.stack([bl.pack_fp([x.c0 for x in f2s]),
                       bl.pack_fp([x.c1 for x in f2s])])
    got_s = np.asarray(blh.sgn0_f2(jnp.asarray(packed)))
    assert got_s.tolist() == [x.sgn0() for x in f2s]


def test_hash_to_g2_matches_host():
    sqrt_g, x_g = getters()
    msgs = [b"blh2c-%d" % i for i in range(B)]
    u = xh2c.msgs_to_u(msgs)          # (B, 2, 2, 32) batch-leading
    u_bl = jnp.asarray(np.moveaxis(u, 0, -1))  # (2, 2, 32, B)
    pt = blh.hash_to_g2_bl(u_bl, blc.F2, sqrt_g, x_g)
    got = blc.unpack_g2_points(pt)
    want = [hh.hash_to_g2(m) for m in msgs]
    assert got == want


def test_decompress_and_subgroup_matches_host():
    sqrt_g, x_g = getters()
    sigs = []
    for i in range(B - 1):
        sigs.append(PointG2.generator().mul(
            rng.randrange(1, 1 << 128)).to_bytes())
    # an x with no curve point: tweak a valid sig's x until decompression
    # fails on host
    bad = bytearray(sigs[0])
    while True:
        bad[5] = (bad[5] + 1) % 256
        try:
            PointG2.from_bytes(bytes(bad), subgroup_check=False)
        except ValueError:
            break
    sigs.append(bytes(bad))
    xs, sign, valid = xh2c.sigs_to_x(sigs)
    assert valid[:B - 1].all() and valid[B - 1]  # byte-valid, not on curve
    x_bl = jnp.asarray(np.moveaxis(xs, 0, -1))
    pt, on_curve = blh.decompress_g2_bl(x_bl, jnp.asarray(sign), blc.F2,
                                        sqrt_g)
    on_curve = np.asarray(on_curve)
    assert on_curve[:B - 1].all() and not on_curve[B - 1]
    got = blc.unpack_g2_points(pt)[:B - 1]
    want = [PointG2.from_bytes(s) for s in sigs[:B - 1]]
    assert got == want
    in_sub = np.asarray(blc.subgroup_check(blc.F2, pt, x_g))
    assert in_sub[:B - 1].all()
