"""Chaos network simulator (ISSUE 11): scripted fault schedules whose
assertion surface is the observability stack — quorum margins,
contribution bitmaps, reachability/partition-suspect gauges, /healthz
lag thresholds, ingress-reject counters, DKG phase timelines. No
scenario peeks at protocol internals.

Late-alphabet filename per the tier-1 chunking convention (ROADMAP
operational constraint). Everything here is host-only: the structural
crypto mode replaces the pairing-class leaves, so no device graphs and
no fresh XLA compiles.
"""

import asyncio
import json
import logging

import aiohttp
import grpc
import grpc.aio
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.obs.flight import FLIGHT
from drand_tpu.obs.health import HEALTH, READY_MAX_LAG
from drand_tpu.obs.state import isolated_observability
from drand_tpu.testing.chaos import (ChaosBeaconNetwork, FaultEvent,
                                     LinkPolicy, detection_lead,
                                     recovery_seconds, structural_crypto)

PERIOD = 4


def _rejects(source, verdict):
    return _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_ingress_rejects",
                         source=source, verdict=verdict)


async def _get(port, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}") as r:
            try:
                body = await r.json()
            except Exception:  # noqa: BLE001 — non-JSON error bodies
                body = {}
            return r.status, body


# ---------------------------------------------------------------------------
# 1. the acceptance scenario: margin degrades BEFORE missed fires
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_margin_degrades_rounds_before_missed_fires():
    """Healthy rounds hold margin ≈ period; a cross-link delay fault
    drags the quorum margin under period/2 for several rounds while
    beacon_rounds_missed_total stays flat; only the subsequent no-quorum
    partition moves the missed counter — the early-warning SLI
    demonstrably led the failure. After heal, catch-up closes the lag
    (recovery measured through the same surfaces)."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=8, t=5, period=PERIOD)
        q0 = _sample_count(metrics.GROUP_REGISTRY,
                           "beacon_quorum_margin_seconds")
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(4, "link_all",
                       {"policy": LinkPolicy(delay_s=2.5)}),
            FaultEvent(7, "partition",
                       {"groups": [[0, 1, 2, 3], [4, 5, 6, 7]]}),
            FaultEvent(11, "heal"),
        ]
        obs = await net.run_schedule(sched, rounds=14)
        net.stop_all()

        by_round = {ob.round: ob for ob in obs}
        first = obs[0].round
        # healthy phase: quorum landed on the boundary, full margin
        for r in range(first, 4):
            assert by_round[r].margin_s == pytest.approx(PERIOD)
            assert by_round[r].missed_total == 0
        # degraded phase: margin = period - delay, under the warn line,
        # while the missed counter has still never moved
        for r in range(4, 7):
            assert by_round[r].margin_s == pytest.approx(
                PERIOD - 2.5, abs=0.3)
            assert by_round[r].margin_s < PERIOD / 2
            assert by_round[r].missed_total == 0
        lead = detection_lead(obs, PERIOD)
        assert lead["warn_round"] == 4
        assert lead["missed_round"] is not None
        assert lead["lead_rounds"] >= 3
        # the partition (both fragments < t) is what finally fires it
        assert max(ob.missed_total for ob in obs) >= 3
        # the partitioned probe fingers the other fragment as suspects
        assert by_round[8].suspects == 4
        # heal: lag returns to 0 within a bounded catch-up window
        rec = recovery_seconds(obs, 11, PERIOD)
        assert rec is not None and rec <= 4 * PERIOD
        assert obs[-1].margin_s == pytest.approx(PERIOD)
        assert obs[-1].suspects == 0
        # the margin SLI observed samples throughout
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_quorum_margin_seconds") > q0


# ---------------------------------------------------------------------------
# 2. the bitmap fingers exactly the faulted peer set
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_bitmap_fingers_exact_faulted_peer_set():
    """Crash node 5 and corrupt node 4 (garbage partials under its own
    index): the honest probe's contribution bitmap settles on exactly
    {4: '!', 5: '.'} with every honest column on time, and the per-peer
    invalid counter charges only the byzantine index."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=6, t=4, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(3, "crash", {"nodes": [5]}),
            FaultEvent(3, "byzantine", {"node": 4, "kind": "garbage"}),
        ]
        obs = await net.run_schedule(sched, rounds=6)
        net.stop_all()

        faulted_rounds = [ob for ob in obs if ob.round >= 4]
        assert faulted_rounds
        for ob in faulted_rounds:
            assert ob.stored, "quorum (t=4 of 4 honest) must survive"
            assert ob.bitmap[5] == ".", ob.bitmap
            assert ob.bitmap[4] in "!.", ob.bitmap
            for honest in range(4):
                assert ob.bitmap[honest] in "#~", ob.bitmap
        # at least one round caught the byzantine partial in its ring
        assert any(ob.bitmap[4] == "!" for ob in faulted_rounds)
        # faulted set == {4, 5}, exactly
        fingered = {i for ob in faulted_rounds
                    for i in range(6) if ob.bitmap[i] in "!."}
        assert fingered == {4, 5}
        peers = net.flight(0).peers()
        assert peers["4"]["invalid"] > 0
        for honest in range(4):
            assert peers[str(honest)]["invalid"] == 0
        # the crashed node is dark, not framed: no invalid charged to 5
        assert peers.get("5", {}).get("invalid", 0) == 0


# ---------------------------------------------------------------------------
# 3. /healthz and /readyz transition at the documented lag threshold
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_healthz_readyz_transition_at_documented_lag():
    """Quorum loss (t crashed) stalls the chain: /healthz flips 200 ->
    503 exactly past DRAND_TPU_READY_MAX_LAG rounds of lag, /readyz
    agrees, the sync-stall gauge rises through the same probe, and the
    restart storm brings both back to 200."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=4, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        server = PublicServer(DirectClient(net.handlers[0]),
                              clock=net.clocks[0])
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        try:
            status, body = await _get(port, "/healthz")
            assert status == 200 and body["status"] == "ok"
            assert body["lag_rounds"] <= body["max_lag"] == READY_MAX_LAG
            status, body = await _get(port, "/readyz")
            assert status == 200 and body["ready"] is True

            # kill quorum: only 3 of t=4 members remain
            for i in (3, 4):
                net.crash(i)
            # within the documented bound the probe still reports ok
            await net.run_schedule([], rounds=READY_MAX_LAG)
            status, body = await _get(port, "/healthz")
            assert status == 200, body
            # one more lagging round crosses the threshold: 503 + stall
            await net.run_schedule([], rounds=2)
            status, body = await _get(port, "/healthz")
            assert status == 503 and body["status"] == "lagging"
            assert body["lag_rounds"] > body["max_lag"]
            assert body["sync_stalled"] is True
            assert metrics.SYNC_STALLED._value.get() == 1
            status, body = await _get(port, "/readyz")
            assert status == 503 and body["ready"] is False
            assert "head lag" in body["reason"]
            missed_mid = _sample_count(metrics.GROUP_REGISTRY,
                                       "beacon_rounds_missed")
            assert missed_mid > 0

            # restart storm: the members return and the chain catches up
            for i in (3, 4):
                await net.restart(i)
            for _ in range(6):
                await net.advance_round()
                status, body = await _get(port, "/healthz")
                if status == 200:
                    break
            assert status == 200 and body["status"] == "ok"
            assert body["sync_stalled"] is False
            status, body = await _get(port, "/readyz")
            assert status == 200 and body["ready"] is True
        finally:
            await server.stop()
            net.stop_all()


# ---------------------------------------------------------------------------
# 4. per-node clock skew
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_clock_skew_flags_late_peer_and_degrades_margin():
    """A node whose clock runs 3 s behind broadcasts that much after
    every boundary: with t=3 of 4 punctual peers the quorum is safe,
    but once a second node is dark the skewed partial IS the t-th —
    the margin degrades by exactly the skew and the bitmap marks the
    peer late ('~')."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(3, "skew", {"node": 3, "seconds": -3.0}),
            FaultEvent(3, "crash", {"nodes": [4]}),
            FaultEvent(3, "crash", {"nodes": [2]}),
        ]
        obs = await net.run_schedule(sched, rounds=5)
        net.stop_all()

        skewed = [ob for ob in obs if ob.round >= 4]
        assert skewed
        for ob in skewed:
            assert ob.stored
            # quorum waits for the skewed node: margin = period - skew
            assert ob.margin_s == pytest.approx(PERIOD - 3.0, abs=0.3)
            assert ob.bitmap[3] == "~", ob.bitmap
        peers = net.flight(0).peers()
        assert peers["3"]["late"] >= len(skewed)
        assert peers["0"]["late"] == 0


# ---------------------------------------------------------------------------
# 5. garbage floods: DoS posture + the reject counter closes the gap
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_flood_dos_posture_and_reject_visibility():
    """An attacker floods one node with stale/future/garbage partials:
    every rejection lands on beacon_ingress_rejects_total{grpc,verdict}
    (the new chaos-surfaced SLI — floods were invisible before), no
    flood round ever evicts live flight records, out-of-group claims
    are never attributed, and the chain keeps storing on the boundary."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        # the attacker crafts off the public chain tip
        head_b = net.stores[0].last()
        head, head_sig = head_b.round, head_b.signature
        r0_stale = _rejects("grpc", "stale")
        r0_future = _rejects("grpc", "future")
        r0_invalid = _rejects("grpc", "invalid")

        stale = [net.make_bad_partial(1, 1, prev_sig=b"\x00" * 96)
                 for _ in range(10)]
        future = [net.make_bad_partial(head + 50, 1) for _ in range(10)]
        garbage = [net.make_bad_partial(head + 1, 2, kind="garbage",
                                        prev_sig=head_sig)
                   for _ in range(10)]
        outofgroup = [net.make_bad_partial(head + 1, 999, kind="garbage",
                                           prev_sig=head_sig)]
        n_rej = await net.inject_partials(
            stale + future + garbage + outofgroup, targets=[0])
        assert n_rej == 31  # every crafted packet was rejected

        assert _rejects("grpc", "stale") == r0_stale + 10
        assert _rejects("grpc", "future") == r0_future + 10
        assert _rejects("grpc", "invalid") == r0_invalid + 11
        # in-window garbage charged the claimed in-group index only
        peers = net.flight(0).peers()
        assert peers["2"]["invalid"] == 10
        assert "999" not in peers
        # live records survived the flood and the chain still advances
        assert net.flight(0).rounds(4), "flood evicted live records"
        obs = await net.run_schedule([], rounds=2)
        net.stop_all()
        for ob in obs:
            assert ob.stored and ob.missed_total == 0
            assert ob.margin_s == pytest.approx(PERIOD)


# ---------------------------------------------------------------------------
# 6. rolling crash-restart storm
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_rolling_crash_restart_storm_never_loses_quorum():
    """A rolling storm (two nodes down at a time, restarting as the
    next pair drops) stays above t the whole way: zero missed rounds,
    reachability dips exactly while peers are down, and the final
    bitmap returns to full participation."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=8, t=5, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(3, "crash", {"nodes": [1, 2]}),
            FaultEvent(5, "restart", {"nodes": [1, 2]}),
            FaultEvent(5, "crash", {"nodes": [3, 4]}),
            FaultEvent(7, "restart", {"nodes": [3, 4]}),
            FaultEvent(7, "crash", {"nodes": [5, 6]}),
            FaultEvent(9, "restart", {"nodes": [5, 6]}),
        ]
        obs = await net.run_schedule(sched, rounds=10)
        net.stop_all()

        for ob in obs:
            assert ob.stored, f"round {ob.round} missed during the storm"
            assert ob.missed_total == 0
        # suspects tracked the storm and cleared after it
        assert max(ob.suspects for ob in obs) >= 2
        assert obs[-1].suspects == 0
        assert obs[-1].bitmap == "#" * 8
        # every send outcome landed on the per-peer counter
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_peer_sends", outcome="failed") > 0


# ---------------------------------------------------------------------------
# 7. mid-ceremony reshare under churn
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_reshare_under_churn_stalls_in_the_right_phase():
    """A reshare with one silent dealer while beacon rounds keep
    ticking: the DKG timeline shows the deal phase running its FULL
    phaser window (the stall is visible in the right phase), the
    complaint map names exactly the silent dealer, QUAL excludes it,
    dkg_phase_seconds observed samples — and the beacon chain never
    missed a round during the ceremony."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        d0 = _sample_count(metrics.GROUP_REGISTRY, "dkg_phase_seconds",
                           phase="deal")
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        results = await net.reshare_under_churn({4}, phase_timeout=10.0)
        obs = await net.run_schedule([], rounds=2)
        net.stop_all()

        sessions = FLIGHT.dkg.sessions()
        assert len(sessions) == 4
        for s in sessions:
            assert s["mode"] == "reshare" and s["done"]
            assert s["error"] is None
            assert s["qual"] == [0, 1, 2, 3]
            assert s["complaints"] == {"4": [0, 1, 2, 3]}
            assert sorted(s["bundles"]["deal"]) == ["0", "1", "2", "3"]
            phases = [p["phase"] for p in s["phases"]]
            assert phases == ["deal", "response", "justification",
                              "finish"]
            deal = s["phases"][0]
            # fast-sync could not fire (4 of 5 dealers): the deal phase
            # ran its whole 10 s window — the stall, in the right phase
            assert deal["end_s"] - deal["start_s"] == pytest.approx(10.0)
        assert _sample_count(metrics.GROUP_REGISTRY, "dkg_phase_seconds",
                             phase="deal") >= d0 + 4
        assert all(r.qual == [0, 1, 2, 3] for r in results)
        # the chain rode through the ceremony: no missed rounds
        for ob in obs:
            assert ob.stored and ob.missed_total == 0


# ---------------------------------------------------------------------------
# 8. gossip flood: ban machinery + the reject counter
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_gossip_flood_is_counted_then_banned():
    """A flood of invalid beacons into a gossip node's real Publish
    port lands every rejection on beacon_ingress_rejects_total
    {source=gossip} until the source IP trips the ban; once banned,
    further publishes are refused at the door (PERMISSION_DENIED) —
    observable to the flooder itself — and no flood message was ever
    cached or re-forwarded (the tip never moved)."""
    from drand_tpu.net import wire
    from drand_tpu.relay import gossip as g
    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.info import Info
    from drand_tpu.utils.clock import FakeClock

    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=3, t=2, period=PERIOD)
        info = Info.from_group(net.group)
        clock = FakeClock(start=info.genesis_time + 1000)
        node = g.GossipNode(info, clock=clock)
        await node.serve("127.0.0.1:0")
        ch = grpc.aio.insecure_channel(f"127.0.0.1:{node.port}")
        publish = ch.unary_unary(f"/{g.SERVICE}/Publish")
        r0 = _rejects("gossip", "invalid")
        try:
            banned = 0
            for i in range(g.SCORE_INVALID_LIMIT + 5):
                bad = Beacon(round=2 + i % 3,
                             previous_sig=bytes([i]) * 96,
                             signature=b"\x99" * 96)
                try:
                    await publish(wire.encode(bad), timeout=5.0)
                except grpc.aio.AioRpcError as e:
                    assert e.code() == grpc.StatusCode.PERMISSION_DENIED
                    banned += 1
            # the ban tripped mid-flood and refused the rest at the door
            assert banned >= 5
            rejected = _rejects("gossip", "invalid") - r0
            assert rejected >= g.SCORE_INVALID_LIMIT
            # nothing was cached or re-forwarded
            assert node._tip == 0
        finally:
            await ch.close()
            await node.stop()


# ---------------------------------------------------------------------------
# 9. secret hygiene under faults (real crypto)
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_no_secret_reaches_logs_metrics_or_flight_under_faults(
        caplog):
    """The PR-10 hygiene check under fault load, with REAL crypto so
    the shares actually flow: run crash + flood faults at debug logging
    and assert no node's secret share (decimal or hex) appears in any
    log line, the /metrics exposition, the flight dump, or the health
    snapshot."""
    caplog.set_level(logging.DEBUG)
    with isolated_observability():
        net = ChaosBeaconNetwork(n=3, t=2, period=PERIOD,
                                 log_level="debug")
        for name in list(logging.Logger.manager.loggerDict):
            if name.startswith("chaos"):
                logging.getLogger(name).setLevel(logging.DEBUG)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [FaultEvent(2, "crash", {"nodes": [2]})]
        obs = await net.run_schedule(sched, rounds=2)
        head = net.stores[0].last()
        await net.inject_partials(
            [net.make_bad_partial(head.round + 1, 1, kind="garbage",
                                  prev_sig=head.signature)],
            targets=[0])
        net.stop_all()
        assert any(ob.stored for ob in obs), "no rounds under real crypto"

        blob = "\n".join(r.getMessage() for r in caplog.records)
        blob += metrics.render().decode()
        blob += json.dumps({"rounds": net.flight(0).rounds(16),
                            "peers": net.flight(0).peers(),
                            "reach": net.flight(0).reachability()})
        blob += json.dumps(HEALTH.snapshot())
        for share in net.shares:
            secret = share.pri_share.value
            assert str(secret) not in blob
            assert format(secret, "x") not in blob
        assert "pri_share" not in blob


# ---------------------------------------------------------------------------
# 10. asymmetric partition: inbound-only cut, quorum repair pulls across it
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_asymmetric_partition_repair_pulls_across_the_cut():
    """Every peer's calls TO node 0 are denied while node 0's outbound
    still works — the asymmetric fault the symmetric partition action
    cannot model. Node 0's sender-side view stays clean (all sends
    succeed: zero suspects, full reachability) even though it receives
    NOTHING — but its peers reached quorum without it and flushed
    their collectors, so the repair pull comes back answered-empty and
    the monitor's SYNC leg fetches the stored beacon instead: every
    round lands on node 0 inside its own period (stored, zero missed)
    without a local quorum ever forming (margin honestly None). The
    healthy side never notices (node 0's partials arrive fine)."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        sy0 = _sample_count(metrics.GROUP_REGISTRY,
                            "beacon_partial_repairs", outcome="synced")
        sched = [FaultEvent(3, "deny", {"src": i, "dst": 0})
                 for i in range(1, 5)]
        sched += [FaultEvent(8, "heal")]
        obs = await net.run_schedule(sched, rounds=8)
        net.stop_all()

        cut = [ob for ob in obs if 3 <= ob.round < 8]
        assert cut
        for ob in cut:
            # recovered IN-PERIOD via the sync leg: the beacon is on
            # node 0's chain before its round ends, missed never moves
            assert ob.stored, f"round {ob.round} not recovered in-period"
            assert ob.missed_total == 0
            # no local quorum: the margin SLI stays honestly empty
            assert ob.margin_s is None
            # the asymmetric signature: the victim's own sender-side
            # view is clean — no suspects, nothing unreachable
            assert ob.suspects == 0
        assert all(net.flight(0).reachability().values())
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_partial_repairs",
                             outcome="synced") > sy0
        # healed: local quorum returns, margins back to the full period
        assert obs[-1].margin_s == pytest.approx(PERIOD)
        # the healthy side held full margins throughout (node 0's
        # outbound partials kept arriving)
        ob4 = net.observe(cut[0].round, probe=4)
        assert ob4.margin_s == pytest.approx(PERIOD)


# ---------------------------------------------------------------------------
# 11. slow-loris links: stale rejects never trip the breaker (half-open too)
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_slow_loris_rejects_never_trip_breaker_then_half_open_recloses():
    """Node 4's partials crawl (1.5 periods of link delay), arriving
    past every receiver's window: each lands an answered STALE reject
    back on the sender. PeerRejectedError immunity says those must
    never trip node 4's breakers — its sender-side view stays fully
    reachable, breaker gauges stay closed. Then a real partition trips
    the survivors' breakers toward 4 (OPEN on the gauge), and after
    heal the capped half-open probe re-closes them within a round —
    the breaker's full state cycle under one schedule."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        s0 = _rejects("grpc", "stale")
        loris = [FaultEvent(3, "link",
                            {"src": 4, "dst": d,
                             "policy": LinkPolicy(delay_s=1.5 * PERIOD)})
                 for d in range(4)]
        obs = await net.run_schedule(loris, rounds=4)

        for ob in obs[-2:]:
            # quorum rides the 4 punctual members: full margin, and the
            # slow peer's column reads missing (its partial never lands
            # in-window)
            assert ob.stored and ob.margin_s == pytest.approx(PERIOD)
            assert ob.bitmap[4] == ".", ob.bitmap
        # the crawling partials came back as answered stale rejects...
        assert _rejects("grpc", "stale") > s0
        # ...and did NOT trip the slow sender's breakers: its view is
        # all-reachable, every breaker gauge still closed
        assert all(net.flight(4).reachability().values())
        for idx in range(5):
            assert metrics.PEER_BREAKER_STATE.labels(
                index=str(idx))._value.get() == 0, idx
        for br in net.handlers[4]._breakers.values():
            assert br.state == 0

        # now a REAL fault: node 4 unreachable for two rounds
        part = [FaultEvent(7, "partition",
                           {"groups": [[0, 1, 2, 3], [4]]})]
        await net.run_schedule(part, rounds=2)
        assert metrics.PEER_BREAKER_STATE.labels(
            index="4")._value.get() == 2  # OPEN on the survivors
        obs = await net.run_schedule([FaultEvent(9, "heal")], rounds=3)
        net.stop_all()
        # half-open probe succeeded after heal: breaker re-closed and
        # the group is whole again
        assert metrics.PEER_BREAKER_STATE.labels(
            index="4")._value.get() == 0
        assert obs[-1].stored and obs[-1].suspects == 0
        assert obs[-1].bitmap[4] in "#~", obs[-1].bitmap


# ---------------------------------------------------------------------------
# 12. reshare + partition combo: ceremony stalls cleanly, chain never misses
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_reshare_under_partition_names_dealer_and_never_misses():
    """The PR-11 reshare-under-churn scenario with the silent dealer
    also PARTITIONED off the beacon plane: the ceremony still stalls in
    exactly the deal phase and the complaint map names the partitioned
    dealer, while the majority's beacon chain rides through with zero
    missed rounds and the partition is visible as exactly one suspect;
    after heal the group returns to full participation."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=5, t=3, period=PERIOD)
        await net.start_all()
        await net.advance_to_genesis()
        await net.run_schedule([], rounds=2)
        net.partition([[0, 1, 2, 3], [4]])
        results = await net.reshare_under_churn({4}, phase_timeout=10.0)
        obs_part = await net.run_schedule([], rounds=1)
        net.heal()
        net.network.allow_all()
        obs = await net.run_schedule([], rounds=3)
        net.stop_all()

        sessions = FLIGHT.dkg.sessions()
        assert len(sessions) == 4
        for s in sessions:
            assert s["mode"] == "reshare" and s["done"]
            assert s["qual"] == [0, 1, 2, 3]
            assert s["complaints"] == {"4": [0, 1, 2, 3]}
            deal = s["phases"][0]
            assert deal["phase"] == "deal"
            assert deal["end_s"] - deal["start_s"] == pytest.approx(10.0)
        assert all(r.qual == [0, 1, 2, 3] for r in results)
        # the chain never missed a round through ceremony + partition,
        # and the partitioned dealer shows as exactly one suspect
        ob = obs_part[-1]
        assert ob.stored and ob.missed_total == 0
        assert ob.suspects == 1
        # healed: suspects clear and the group contributes fully again
        assert obs[-1].missed_total == 0
        assert obs[-1].suspects == 0
        assert obs[-1].bitmap[4] in "#~", obs[-1].bitmap
