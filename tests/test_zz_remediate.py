"""Closed-loop auto-remediation (ISSUE 16): playbook-engine guardrails
(budget, cooldown, dry-run default, failure ledgering), the bounded
supervisor, the reshare-recommendation builder, analyzer fixtures for
the new ledger sinks and lock discipline, the chaos-oracle e2e matrix
(sync_stall, breaker_open, reachability_drop, worker death — incident
mints -> playbook fires -> network recovers with zero operator
intervention -> the bundle carries the full remediation ledger), and
the /debug/remediation route's shared ?n= contract.

Late-alphabet filename per the tier-1 chunking convention
(tools/tier1_chunks.sh). Host-only: chaos scenarios run under
structural crypto — no device graphs, no fresh XLA compiles.
"""

import asyncio
import textwrap

import aiohttp
import pytest
from aiohttp import web
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.debug import add_trace_routes
from drand_tpu.http_server.server import PublicServer
from drand_tpu.net.transport import BREAKER_OPEN
from drand_tpu.obs.flight import FlightRecorder
from drand_tpu.obs.health import HealthState
from drand_tpu.obs.incident import INCIDENTS, IncidentManager, Rule
from drand_tpu.obs.remediate import (ENGINE, PLAYBOOK_PULL,
                                     PLAYBOOK_RESPAWN, PLAYBOOK_SYNC,
                                     Playbook, PlaybookEngine,
                                     attach_node, attach_posture,
                                     attach_supervisor,
                                     configure_from_env,
                                     default_playbooks,
                                     reshare_recommendation,
                                     worker_down_rule)
from drand_tpu.obs.state import isolated_observability
from drand_tpu.testing.chaos import (ChaosBeaconNetwork, FaultEvent,
                                     structural_crypto)
from drand_tpu.utils.aio import spawn as aio_spawn
from drand_tpu.utils.clock import FakeClock
from drand_tpu.utils.supervise import (ALIVE, BACKOFF, BUDGET_EXHAUSTED,
                                       RESPAWN_FAILED, RESPAWNED,
                                       UNKNOWN, Supervisor)
from tools.analyze import lockheld, secretflow
from tools.analyze.core import Project

PERIOD = 4


async def _get(port, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}") as r:
            try:
                body = await r.json()
            except Exception:  # noqa: BLE001 — non-JSON error bodies
                body = {}
            return r.status, body


async def _drain():
    for _ in range(10):
        await asyncio.sleep(0)


def _fault_rule(fault):
    """An incident rule firing while the injected fault flag is on."""
    return Rule("custom", "warning", "edge",
                lambda w, ctx: "down" if fault["on"] else None,
                cooldown_s=0.0, clear_after=2)


def _engine_with(clk, fault, *, playbook: Playbook, **kw):
    mgr = IncidentManager(flight=FlightRecorder(), health=HealthState(),
                          rules=[_fault_rule(fault)])
    engine = PlaybookEngine(clock=clk, playbooks=[playbook], **kw)
    engine.attach(mgr)
    return mgr, engine


# ---------------------------------------------------------------------------
# 1. guardrails — the acceptance quartet
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_budget_exhaustion_stops_firing_keeps_annotating():
    """Past the global budget the engine STOPS acting but keeps
    writing budget_exhausted refusals into the ledger and the
    incident's bundle — silence is the one unacceptable outcome."""
    clk, fault, calls = FakeClock(1000.0), {"on": True}, []
    pb = Playbook("custom", rule="custom", describe="poke the subsystem",
                  cooldown_s=0.0)
    mgr, engine = _engine_with(clk, fault, playbook=pb, dry_run=False,
                               max_actions=2, window_s=3600.0)

    async def act(summary):
        calls.append(summary["id"])
        return "poked"

    engine.register_action("custom", act)
    for r in range(1, 6):
        mgr.on_round(r, now=clk.now(), period=PERIOD)
        await clk.advance(PERIOD)
        await _drain()
    assert len(calls) == 2
    outcomes = [e["outcome"] for e in engine.ledger(16)]
    assert outcomes.count("ok") == 2
    assert outcomes.count("budget_exhausted") == 3
    [inc] = mgr.incidents()
    bundle = mgr.get_bundle(inc["id"])
    refusals = [e for e in bundle["remediation"]
                if e["outcome"] == "budget_exhausted"]
    assert len(refusals) == 3
    assert "not running" in refusals[0]["detail"]
    assert engine.status()["budget"]["used"] == 2


@pytest.mark.asyncio
async def test_cooldown_dedups_sustained_fault_to_one_action():
    """A fault firing every sample inside the playbook cooldown runs
    ONE action — and the skip is silent (no ledger spam)."""
    clk, fault, calls = FakeClock(1000.0), {"on": True}, []
    pb = Playbook("custom", rule="custom", describe="poke",
                  cooldown_s=1000.0)
    mgr, engine = _engine_with(clk, fault, playbook=pb, dry_run=False,
                               max_actions=8, window_s=3600.0)

    async def act(summary):
        calls.append(summary["id"])
        return "poked"

    engine.register_action("custom", act)
    for r in range(1, 7):
        mgr.on_round(r, now=clk.now(), period=PERIOD)
        await clk.advance(PERIOD)
        await _drain()
    assert len(calls) == 1
    assert [e["outcome"] for e in engine.ledger(16)] == ["ok"]
    # past the cooldown the still-open incident earns a second action
    await clk.advance(1000.0)
    mgr.on_round(7, now=clk.now(), period=PERIOD)
    await _drain()
    assert len(calls) == 2


@pytest.mark.asyncio
async def test_dry_run_default_annotates_without_touching_state(
        monkeypatch):
    """With DRAND_TPU_REMEDIATE unset the engine is dry-run: the
    registered action NEVER runs, but every decision is annotated into
    the ledger and the incident bundle as what it WOULD have done."""
    monkeypatch.delenv("DRAND_TPU_REMEDIATE", raising=False)
    clk, fault, calls = FakeClock(1000.0), {"on": True}, []
    pb = Playbook("custom", rule="custom", describe="poke the subsystem",
                  cooldown_s=0.0)
    mgr, engine = _engine_with(clk, fault, playbook=pb,
                               max_actions=8, window_s=3600.0)
    assert engine.dry_run

    async def act(summary):
        calls.append(summary["id"])
        return "poked"

    engine.register_action("custom", act)
    for r in range(1, 4):
        mgr.on_round(r, now=clk.now(), period=PERIOD)
        await clk.advance(PERIOD)
        await _drain()
    assert calls == []
    entries = engine.ledger(16)
    assert len(entries) == 3
    assert all(e["outcome"] == "dry_run" for e in entries)
    assert all(e["detail"] == "would: poke the subsystem"
               for e in entries)
    [inc] = mgr.incidents()
    bundle = mgr.get_bundle(inc["id"])
    assert [e["outcome"] for e in bundle["remediation"]] == \
        ["dry_run"] * 3
    # dry-run dispatches consume NO live budget
    assert engine.status()["budget"]["used"] == 0


@pytest.mark.asyncio
async def test_failed_action_records_outcome_without_reminting():
    """An action that raises lands outcome=failed (exception text in
    the ledger), clears the active marker, and mints no extra
    incident."""
    clk, fault = FakeClock(1000.0), {"on": True}
    pb = Playbook("custom", rule="custom", describe="poke",
                  cooldown_s=1000.0)
    mgr, engine = _engine_with(clk, fault, playbook=pb, dry_run=False,
                               max_actions=8, window_s=3600.0)

    async def act(summary):
        raise RuntimeError("subsystem said no")

    engine.register_action("custom", act)
    for r in range(1, 4):
        mgr.on_round(r, now=clk.now(), period=PERIOD)
        await clk.advance(PERIOD)
        await _drain()
    [entry] = engine.ledger(16)
    assert entry["outcome"] == "failed"
    assert "RuntimeError: subsystem said no" in entry["detail"]
    assert len(mgr.incidents()) == 1
    assert engine.status()["active"] == {}
    # a playbook with NO registered action fails the same audited way
    mgr2, engine2 = _engine_with(clk, fault, playbook=pb, dry_run=False,
                                 max_actions=8, window_s=3600.0)
    mgr2.on_round(1, now=clk.now(), period=PERIOD)
    await _drain()
    [e2] = engine2.ledger(4)
    assert e2["outcome"] == "failed"
    assert "no action registered" in e2["detail"]


# ---------------------------------------------------------------------------
# 2. the bounded supervisor
# ---------------------------------------------------------------------------

def test_supervisor_budget_backoff_and_status():
    alive, spawned = {"on": False}, []
    sup = Supervisor(clock=FakeClock(100.0), respawn_budget=2,
                     backoff_base_s=1.0, backoff_cap_s=8.0)
    sup.register("w", is_alive=lambda: alive["on"],
                 respawn=lambda: spawned.append(True))
    assert sup.dead() == ["w"]
    assert sup.maybe_respawn("w", now=100.0) == RESPAWNED
    # inside the backoff window the retry is refused, slot unspent
    assert sup.maybe_respawn("w", now=100.5) == BACKOFF
    assert sup.maybe_respawn("w", now=101.2) == RESPAWNED
    assert sup.maybe_respawn("w", now=200.0) == BUDGET_EXHAUSTED
    assert len(spawned) == 2
    alive["on"] = True
    assert sup.maybe_respawn("w", now=300.0) == ALIVE
    assert sup.maybe_respawn("nope", now=300.0) == UNKNOWN
    st = sup.status()["w"]
    assert st["alive"] and st["respawns"] == 2 and st["budget"] == 2


def test_supervisor_failed_respawn_spends_the_slot():
    """A respawn callable that raises still burns its budget slot and
    its backoff window — a crash-looping spawner cannot retry-storm."""
    sup = Supervisor(clock=FakeClock(100.0), respawn_budget=2,
                     backoff_base_s=5.0)

    def boom():
        raise OSError("fork failed")

    sup.register("w", is_alive=lambda: False, respawn=boom)
    assert sup.maybe_respawn("w", now=100.0) == RESPAWN_FAILED
    assert sup.respawns("w") == 1
    assert sup.maybe_respawn("w", now=101.0) == BACKOFF
    assert sup.check(now=106.0)["w"] == RESPAWN_FAILED
    assert sup.maybe_respawn("w", now=600.0) == BUDGET_EXHAUSTED


# ---------------------------------------------------------------------------
# 3. the reshare recommendation pins one peer, never ambient noise
# ---------------------------------------------------------------------------

def _fed_flight(bad_by_peer: dict[int, int], rounds: int = 6,
                n: int = 4) -> FlightRecorder:
    flight = FlightRecorder()
    genesis = 1_000_000
    for r in range(1, rounds + 1):
        now = genesis + (r - 1) * PERIOD
        for idx in range(n):
            verdict = ("invalid" if bad_by_peer.get(idx, 0) >= r
                       else "valid")
            flight.note_partial(r, index=idx, source="grpc",
                                verdict=verdict, now=now + 0.2,
                                period=PERIOD, genesis=genesis, n=n,
                                threshold=3)
    return flight


def test_reshare_recommendation_pinned_vs_ambient():
    # peer 2 degraded in every recent round, everyone else clean
    pinned = reshare_recommendation(_fed_flight({2: 6}))
    assert pinned is not None and "peer index 2" in pinned
    assert "reshare" in pinned
    # the same degradation volume spread over two peers is ambient —
    # no single-peer recommendation (reshares are a ceremony)
    assert reshare_recommendation(_fed_flight({1: 3, 3: 3})) is None
    # too little evidence: quiet
    assert reshare_recommendation(_fed_flight({2: 1})) is None
    assert reshare_recommendation(FlightRecorder()) is None


# ---------------------------------------------------------------------------
# 4. analyzer fixtures: ledger sinks + lock discipline in actions
# ---------------------------------------------------------------------------

def _project(tmp_path, files: dict) -> Project:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(str(tmp_path))


def test_secretflow_flags_remediation_ledger_sinks(tmp_path):
    """Key material flowing into record_action / annotate_remediation
    is a HIGH finding — ledger entries ride the incident bundle and
    /debug/remediation, the same trust boundary as a log line."""
    proj = _project(tmp_path, {"app/fix.py": """
        def bad_record(engine, pri_share):
            engine.record_action("sync_resume", "ok",
                                 detail=str(pri_share.value))

        def bad_annotate(mgr, dist_key):
            mgr.annotate_remediation("inc-1", {"detail": hex(dist_key)})

        def good(engine):
            engine.record_action("sync_resume", "ok",
                                 detail="resumed 3 rounds to head 12")
    """})
    findings = secretflow.run(proj)
    got = {(f.symbol.rsplit(".", 1)[-1], f.rule) for f in findings}
    assert ("bad_record", "secret-in-ledger") in got
    assert ("bad_annotate", "secret-in-ledger") in got
    assert "good" not in {s for s, _ in got}
    assert all(f.severity == "high" for f in findings)
    assert all("remediation ledger" in f.message for f in findings)


def test_lockheld_flags_action_holding_manager_lock(tmp_path):
    """A playbook action holding the manager lock across its await is
    the PR-13 deadlock shape — lockheld must flag it HIGH; snapshot
    under the lock, await outside is clean."""
    proj = _project(tmp_path, {"app/engine.py": """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._summary = {}

            async def bad_action(self, handler):
                with self._lock:
                    return await handler.remediate_sync()

            async def good_action(self, handler):
                with self._lock:
                    summary = dict(self._summary)
                return await handler.remediate_sync()
    """})
    findings = lockheld.run(proj)
    got = {(f.symbol.rsplit(".", 1)[-1], f.rule) for f in findings}
    assert ("bad_action", "lock-across-await") in got
    assert "good_action" not in {s for s, _ in got}
    assert all(f.severity == "high" for f in findings)


# ---------------------------------------------------------------------------
# 5. the chaos-oracle e2e matrix: mint -> fire -> recover -> audit
# ---------------------------------------------------------------------------

def _chaos_mgr(net, rule_names):
    from drand_tpu.obs.incident import default_rules

    net.healths[0].note_dkg_complete()
    return IncidentManager(
        flight=net.flights[0], health=net.healths[0],
        rules=[r for r in default_rules() if r.name in rule_names])


def _ledger_by(engine, playbook):
    return [e for e in engine.ledger(32) if e["playbook"] == playbook]


@pytest.mark.asyncio
async def test_e2e_sync_stall_resumes_from_checkpoint():
    """Partition the probe alone; the majority keeps the chain moving,
    the probe's sync stalls and the incident mints; after heal the
    sync_resume playbook pulls the gap from the upstreams with zero
    operator intervention — lag 0, incident closed, full ledger in the
    bundle."""
    with structural_crypto(), isolated_observability():
        # repair=False and a wedged auto catch-up: sync_stall MEANS
        # "lagging with no catch-up progressing" — the beacon loop's
        # own run_sync (and the PR-12 quorum repair) would otherwise
        # close the gap first; this scenario proves the PLAYBOOK path
        net = ChaosBeaconNetwork(n=4, t=3, period=PERIOD, repair=False)

        async def _wedged(*a, **k):
            return None

        net.handlers[0].chain.run_sync = _wedged
        mgr = _chaos_mgr(net, {"sync_stall"})
        engine = PlaybookEngine(
            clock=net.clocks[0], dry_run=False, max_actions=8,
            window_s=3600.0,
            playbooks=[Playbook(PLAYBOOK_SYNC, rule="sync_stall",
                                describe="rotate + resume",
                                cooldown_s=2 * PERIOD)])
        engine.attach(mgr)
        attach_node(engine, net.handlers[0])
        assert engine.n_peers == 3
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(4, "partition", {"groups": [[0], [1, 2, 3]]}),
            FaultEvent(11, "heal"),
        ]
        obs = await net.run_schedule(
            sched, rounds=20,
            on_round=lambda r, now: mgr.on_round(r, now=now,
                                                 period=PERIOD))
        net.stop_all()

        assert obs[-1].lag == 0, obs[-1]
        incs = [i for i in mgr.incidents() if i["rule"] == "sync_stall"]
        assert len(incs) == 1
        assert incs[0]["state"] == "closed"
        entries = _ledger_by(engine, PLAYBOOK_SYNC)
        assert any(e["outcome"] == "ok" for e in entries), entries
        ok = [e for e in entries if e["outcome"] == "ok"][-1]
        assert "resumed from checkpoint" in ok["detail"]
        bundle = mgr.get_bundle(incs[0]["id"])
        assert bundle["remediation"], bundle
        assert [e["playbook"] for e in bundle["remediation"]] == \
            [PLAYBOOK_SYNC] * len(bundle["remediation"])


@pytest.mark.asyncio
async def test_e2e_breaker_open_quorum_pull_closes_breaker():
    """Partition ONE peer away from the probe's majority: its breaker
    opens and the incident mints (min_fired=2 — one blip never pulls);
    after heal the quorum_pull probe answers and the breaker leaves
    OPEN, audited end to end."""
    with structural_crypto(), isolated_observability():
        metrics.PEER_BREAKER_STATE.clear()  # stray gauge children from
        # earlier tests would read as pre-existing open breakers
        net = ChaosBeaconNetwork(n=4, t=3, period=PERIOD)
        mgr = _chaos_mgr(net, {"breaker_open"})
        engine = PlaybookEngine(
            clock=net.clocks[0], dry_run=False, max_actions=8,
            window_s=3600.0,
            playbooks=[Playbook(PLAYBOOK_PULL, rule="breaker_open",
                                describe="pull + half-open probe",
                                cooldown_s=2 * PERIOD, min_fired=2)])
        engine.attach(mgr)
        attach_node(engine, net.handlers[0])
        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(4, "partition", {"groups": [[0, 1, 2], [3]]}),
            FaultEvent(10, "heal"),
        ]
        await net.run_schedule(
            sched, rounds=18,
            on_round=lambda r, now: mgr.on_round(r, now=now,
                                                 period=PERIOD))
        net.stop_all()

        incs = [i for i in mgr.incidents()
                if i["rule"] == "breaker_open"]
        assert len(incs) == 1
        assert incs[0]["state"] == "closed"
        for br in net.handlers[0]._breakers.values():
            assert br.state != BREAKER_OPEN
        entries = _ledger_by(engine, PLAYBOOK_PULL)
        assert entries
        assert mgr.get_bundle(incs[0]["id"])["remediation"]


@pytest.mark.asyncio
async def test_e2e_majority_partition_applies_and_reverts_posture():
    """The probe lands in the partition MINORITY: the sticky posture
    playbook lowers the watcher cap and serves stale; when the
    incident closes the registered revert restores the cap — one
    apply, one revert, both ledgered."""
    with structural_crypto(), isolated_observability():
        net = ChaosBeaconNetwork(n=4, t=3, period=PERIOD)
        mgr = _chaos_mgr(net, {"reachability_drop"})
        engine = PlaybookEngine(
            clock=net.clocks[0], dry_run=False, max_actions=8,
            window_s=3600.0,
            playbooks=[pb for pb in default_playbooks()
                       if pb.name == "partition_posture"])
        engine.attach(mgr)
        engine.n_peers = 3
        server = PublicServer(DirectClient(net.handlers[0]),
                              clock=net.clocks[0])
        attach_posture(engine, server)
        cap_normal = server._max_watchers
        history = []

        def on_round(r, now):
            mgr.on_round(r, now=now, period=PERIOD)
            history.append((r, server._posture, server._max_watchers))

        await net.start_all()
        await net.advance_to_genesis()
        sched = [
            FaultEvent(4, "partition", {"groups": [[0], [1, 2, 3]]}),
            FaultEvent(12, "heal"),
        ]
        await net.run_schedule(sched, rounds=20, on_round=on_round)
        net.stop_all()

        # posture was ON with the cap lowered mid-partition...
        assert any(p and cap < cap_normal for _, p, cap in history), \
            history
        # ...and restored once the incident closed
        assert server._posture is False
        assert server._max_watchers == cap_normal
        incs = [i for i in mgr.incidents()
                if i["rule"] == "reachability_drop"]
        assert len(incs) == 1 and incs[0]["state"] == "closed"
        outcomes = [e["outcome"]
                    for e in _ledger_by(engine, "partition_posture")]
        assert outcomes.count("ok") == 1
        assert outcomes.count("reverted") == 1
        ledger = mgr.get_bundle(incs[0]["id"])["remediation"]
        assert [e["outcome"] for e in ledger].count("reverted") == 1


@pytest.mark.asyncio
async def test_e2e_worker_death_respawns_and_measures_mttr():
    """Crash a member mid-soak: the worker_down incident mints, the
    respawn playbook restarts it through the bounded supervisor, the
    chain recovers and MTTR lands on the histogram — the closed loop
    with zero operator intervention."""
    with structural_crypto(), isolated_observability():
        m0 = _sample_count(metrics.GROUP_REGISTRY,
                           "remediation_mttr_seconds")
        net = ChaosBeaconNetwork(n=6, t=4, period=PERIOD)
        victim = 5
        sup = Supervisor(clock=net.clocks[0], respawn_budget=3,
                         backoff_base_s=PERIOD)
        sup.register(f"node-{victim}",
                     is_alive=lambda: victim not in net.crashed,
                     respawn=lambda: aio_spawn(net.restart(victim)))
        mgr = _chaos_mgr(net, set())
        mgr.rules.append(worker_down_rule(sup, cooldown_s=PERIOD))
        engine = PlaybookEngine(
            clock=net.clocks[0], dry_run=False, max_actions=8,
            window_s=3600.0,
            playbooks=[Playbook(PLAYBOOK_RESPAWN, rule="worker_down",
                                describe="supervised respawn",
                                cooldown_s=PERIOD)])
        engine.attach(mgr)
        attach_supervisor(engine, sup)
        await net.start_all()
        await net.advance_to_genesis()
        sched = [FaultEvent(4, "crash", {"nodes": [victim]})]
        obs = await net.run_schedule(
            sched, rounds=16,
            on_round=lambda r, now: mgr.on_round(r, now=now,
                                                 period=PERIOD))
        net.stop_all()

        assert victim not in net.crashed
        assert obs[-1].lag == 0
        incs = [i for i in mgr.incidents()
                if i["rule"] == "worker_down"]
        assert len(incs) == 1 and incs[0]["state"] == "closed"
        entries = _ledger_by(engine, PLAYBOOK_RESPAWN)
        ok = [e for e in entries if e["outcome"] == "ok"]
        assert ok and "=respawned" in ok[0]["detail"]
        assert sup.respawns(f"node-{victim}") >= 1
        assert mgr.get_bundle(incs[0]["id"])["remediation"]
        # MTTR became a measured SLI: open-to-close observed once
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "remediation_mttr_seconds") == m0 + 1


# ---------------------------------------------------------------------------
# 6. /debug/remediation + util wiring: the shared ?n= contract
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_remediation_route_and_n_matrix(monkeypatch):
    """The debug route serves the singleton engine's status with the
    same hardened ?n= contract as every other ring route, and
    configure_from_env arms/attaches from the documented knobs."""
    with isolated_observability():
        monkeypatch.setenv("DRAND_TPU_REMEDIATE", "live")
        monkeypatch.setenv("DRAND_TPU_REMEDIATE_MAX", "5")
        engine = configure_from_env()
        try:
            assert engine is ENGINE
            assert not engine.dry_run and engine.max_actions == 5
            assert INCIDENTS.engine is engine
            for i in range(5):
                engine.record_action("sync_resume", "ok", incident=None,
                                     mode="live", detail=f"e{i}",
                                     t=float(i))

            app = web.Application()
            add_trace_routes(app)
            runner = web.AppRunner(app)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", 0)
            await site.start()
            port = site._server.sockets[0].getsockname()[1]
            try:
                status, body = await _get(port, "/debug/remediation")
                assert status == 200
                assert body["mode"] == "live" and body["attached"]
                assert body["budget"]["max"] == 5
                names = {p["playbook"] for p in body["playbooks"]}
                assert names == {"sync_resume", "quorum_pull",
                                 "partition_posture", "respawn_worker",
                                 "reshare_recommend"}
                # newest first
                assert [e["detail"] for e in body["ledger"][:2]] == \
                    ["e4", "e3"]
                status, body = await _get(port,
                                          "/debug/remediation?n=2")
                assert status == 200 and len(body["ledger"]) == 2
                # clamp to the engine ring cap
                status, body = await _get(
                    port, "/debug/remediation?n=999999")
                assert status == 200 and len(body["ledger"]) == 5
                for bad in ("zzz", "1.5", "1e3", "0x10", ""):
                    status, _ = await _get(
                        port, f"/debug/remediation?n={bad}")
                    assert status == 400, bad
            finally:
                await runner.cleanup()

            # dry_run is re-read from env on every configure
            monkeypatch.setenv("DRAND_TPU_REMEDIATE", "dry_run")
            assert configure_from_env().dry_run
        finally:
            INCIDENTS.engine = None
            engine.disarm()
