"""CLI `get private` (ECIES round-trip over gRPC) and `util reset`.

Reference: cmd/drand-cli/cli.go command table (getPrivateCmd, resetCmd),
core/drand_public.go:126 (PrivateRand).
"""

import json
import os

import pytest

from drand_tpu.cli.__main__ import main as cli_main


def _run_cli(argv, capsys):
    cli_main(argv)
    return capsys.readouterr().out


def test_util_reset(tmp_path, capsys):
    folder = tmp_path / "node"
    _run_cli(["generate-keypair", "--folder", str(folder),
              "127.0.0.1:19999"], capsys)
    groups = folder / "groups"
    groups.mkdir(exist_ok=True)
    (groups / "dist_key.private").write_text("share")
    (groups / "drand_group.toml").write_text("group")
    db = folder / "db"
    db.mkdir()
    (db / "chain.db").write_text("x")

    # without --force: refuses
    with pytest.raises(SystemExit):
        _run_cli(["util", "reset", "--folder", str(folder)], capsys)
    assert (groups / "dist_key.private").exists()

    out = _run_cli(["util", "reset", "--folder", str(folder), "--force"],
                   capsys)
    res = json.loads(out.splitlines()[-1])
    assert res["reset"] is True
    assert not (groups / "dist_key.private").exists()
    assert not (groups / "drand_group.toml").exists()
    assert not db.exists()
    # the longterm keypair survives
    assert (folder / "key" / "drand_id.private").exists() or \
        any(p.name.startswith("drand_id") for p in (folder / "key").iterdir())


@pytest.mark.asyncio
async def test_get_private_roundtrip(tmp_path, capsys):
    """Drive the ECIES exchange against a gateway that serves a real
    identity + the daemon's private_rand semantics."""
    from drand_tpu.crypto import ecies
    from drand_tpu.crypto.curves import PointG1
    from drand_tpu.key.keys import new_key_pair
    from drand_tpu.net.grpc_transport import GrpcClient, GrpcGateway
    from drand_tpu.client.private import private_rand

    holder = {}

    class _Svc:
        async def get_identity(self, from_addr):
            return holder["pair"].public

        async def private_rand(self, from_addr, request: bytes) -> bytes:
            client_key = PointG1.from_bytes(
                ecies.decrypt(holder["pair"].key, bytes(request)))
            return ecies.encrypt(client_key, os.urandom(32))

    gw = GrpcGateway(_Svc(), "127.0.0.1:0")
    await gw.start()
    try:
        addr = f"127.0.0.1:{gw.port}"
        # the identity's address is what the client dials for the ECIES
        # exchange — it must carry the real bound port
        holder["pair"] = new_key_pair(addr)
        client = GrpcClient(own_addr="test")
        try:
            ident = await client.get_identity(addr)
            assert ident.valid_signature()
            out = await private_rand(client, ident)
            assert len(out) == 32
        finally:
            await client.close()
    finally:
        await gw.stop()
