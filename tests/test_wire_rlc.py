"""Wire-graph RLC catch-up + endomorphism-Pippenger host MSM (ISSUE 5).

The acceptance criteria pinned here:

- an all-valid catch-up span through the device wire_rlc tier costs
  exactly ONE pairing-graph row = 2 Miller pairs (was 2N), proven by the
  ops/engine.py device pairing-row meter;
- a KAT-gate failure (or a bad signature) falls back to the per-item
  wire graph with exact verdicts — false-reject-only by construction;
- a one-bad-item host span resolves through the batched 4-pairing
  bisection (pairing.pairing_check_groups) with bool arrays
  bit-identical to the per-item loop;
- the ψ-endomorphism-split Pippenger MSM is value-identical to the
  reference windowed MSM, including the split edge scalars 0, 1 and
  2^128-1;
- DRAND_TPU_BATCH_VERIFY=0 disables the wire_rlc tier like every other
  RLC path.

Kept late-alphabet on purpose: the wire graphs are compile-heavy and the
tier-1 chunking note in ROADMAP wants such suites at the tail.
"""

import numpy as np
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.chain.beacon import Beacon, message
from drand_tpu.crypto import batch, batch_verify, bls
from drand_tpu.crypto import pairing as hpairing
from drand_tpu.crypto.curves import PointG1, PointG2


@pytest.fixture(scope="module")
def keys():
    sk, pub = bls.keygen(seed=b"wire-rlc-test")
    return sk, pub


def _make_chain(sk: int, nrounds: int) -> list[Beacon]:
    prev, out = b"\x42" * 32, []
    for rnd in range(1, nrounds + 1):
        sig = bls.sign(sk, message(rnd, prev))
        out.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig
    return out


def _oracle(pub, beacons):
    from drand_tpu.chain import beacon as chain_beacon

    return [chain_beacon.verify_beacon(pub, b) for b in beacons]


# ---------------------------------------------------------------------------
# Host: ψ-split Pippenger MSM vs the windowed reference
# ---------------------------------------------------------------------------

class TestHostMSM:
    def test_pippenger_endo_matches_window_g2(self):
        import random

        rng = random.Random(7)
        g2 = PointG2.generator()
        # edge scalars through the ψ split: 0 (drops out), 1 (rem-only),
        # 2^128-1 (maximal q), M and M±1 (split boundary)
        M = batch_verify._ENDO_M
        pts = [g2.mul(k + 2) for k in range(8)]
        scs = [0, 1, (1 << 128) - 1, M, M - 1, M + 1,
               rng.randrange(1 << 128), rng.randrange(1 << 128)]
        assert batch_verify.msm(pts, scs) == batch_verify.msm_window(pts, scs)
        # a span large enough for the bucket branch
        pts = [g2.mul(rng.randrange(1, 1 << 60)) for _ in range(40)]
        scs = [rng.randrange(1 << 128) for _ in range(40)]
        assert batch_verify.msm(pts, scs) == batch_verify.msm_window(pts, scs)
        # all-zero scalars and infinity points degrade gracefully
        assert batch_verify.msm(pts[:3], [0, 0, 0]).is_infinity()
        got = batch_verify.msm([PointG2.infinity(), g2], [5, 3])
        assert got == g2.mul(3)

    def test_pippenger_matches_window_g1(self):
        import random

        rng = random.Random(11)
        g1 = PointG1.generator()
        pts = [g1.mul(rng.randrange(1, 1 << 60)) for _ in range(20)]
        scs = [rng.randrange(1 << 128) for _ in range(20)]
        assert batch_verify.msm(pts, scs) == batch_verify.msm_window(pts, scs)
        assert batch_verify.msm_pippenger(pts, scs) == \
            batch_verify.msm_window(pts, scs)

    def test_endo_split_reconstructs_scalar(self):
        g2 = PointG2.generator()
        for c in (0, 1, (1 << 128) - 1, batch_verify._ENDO_M, 12345):
            p = g2.mul(9)
            pts, scs = batch_verify._endo_split_g2([p], [c])
            acc = PointG2.infinity()
            for q, s in zip(pts, scs):
                assert s.bit_length() <= batch_verify._ENDO_Q_BITS
                acc = acc + q.mul(s)
            assert acc == p.mul(c)

    def test_gls4_edge_matrix_vs_window(self):
        """The ψ² 4-D split extends msm to full-width scalars: edge
        values (0, 1, M±1, 2^255−19-adjacent, group-order−1) and random
        255-bit scalars are value-identical to the 255-bit windowed
        ladder (ISSUE 8: wide scalars reduce mod r first — same group
        element either way)."""
        import random

        from drand_tpu.crypto import endo
        from drand_tpu.crypto.fields import R

        rng = random.Random(0x615)
        g2 = PointG2.generator()
        M = endo.GLS4_M
        pts = [g2.mul(k + 3) for k in range(10)]
        scs = [0, 1, M - 1, M, M + 1, R - 1, (1 << 255) - 19,
               (1 << 255) - 18, rng.randrange(1 << 255),
               rng.randrange(1 << 254)]
        assert batch_verify.msm(pts, scs) == \
            batch_verify.msm_window(pts, scs, nbits=255)
        # a span wide enough for the bucket branch post-split
        pts = [g2.mul(rng.randrange(1, 1 << 60)) for _ in range(24)]
        scs = [rng.randrange(1 << 255) for _ in range(24)]
        assert batch_verify.msm(pts, scs) == \
            batch_verify.msm_window(pts, scs, nbits=255)

    def test_gls4_split_reconstructs_scalar(self):
        from drand_tpu.crypto import endo
        from drand_tpu.crypto.fields import R

        g2 = PointG2.generator()
        M = endo.GLS4_M
        for c in (1, M, M - 1, R - 1, (1 << 255) - 19):
            p = g2.mul(11)
            pts, scs = batch_verify._endo_split4_g2([p], [c])
            acc = PointG2.infinity()
            for q, s in zip(pts, scs):
                assert s.bit_length() <= endo.GLS4_DIGIT_BITS
                acc = acc + q.mul(s)
            assert acc == p.mul(c % R)


# ---------------------------------------------------------------------------
# Host: batched 4-pairing bisection
# ---------------------------------------------------------------------------

class TestBatchedBisection:
    def test_one_bad_item_grouped_dispatches(self, keys):
        """9-beacon span, one bad signature: root check fails, then each
        bisection level decides BOTH halves with one grouped 4-pairing
        product check. Exact trace: root(2 pairs) + group{0-3, 4-8}(4)
        + group{4-5, 6-8}(4) + leaf(4) + leaf(5) = 5 product-check
        invocations / 14 Miller pairs — the sequential bisection paid 7
        invocations for the same span."""
        sk, pub = keys
        beacons = _make_chain(sk, 9)
        beacons[4].signature = beacons[3].signature
        c0, p0 = hpairing.N_PRODUCT_CHECKS, hpairing.N_MILLER_PAIRS
        got = batch_verify.verify_beacons_rlc(pub, beacons)
        checks = hpairing.N_PRODUCT_CHECKS - c0
        pairs = hpairing.N_MILLER_PAIRS - p0
        oracle = _oracle(pub, beacons)
        assert list(got) == oracle == [True] * 4 + [False] + [True] * 4
        assert checks == 5
        assert pairs == 14

    def test_two_bad_items_still_bit_identical(self, keys):
        sk, pub = keys
        beacons = _make_chain(sk, 12)
        beacons[2].signature = beacons[1].signature
        beacons[9].signature = b"\x00" * 96  # malformed: per-item reject
        got = batch_verify.verify_beacons_rlc(pub, beacons)
        assert list(got) == _oracle(pub, beacons)
        assert list(got) == [True, True, False] + [True] * 6 + [False,
                                                                True, True]

    def test_grouped_pairing_check_primitive(self, keys):
        sk, pub = keys
        from drand_tpu.crypto.hash_to_curve import hash_to_g2

        m1, m2 = b"wrlc-a", b"wrlc-b"
        s1 = PointG2.from_bytes(bls.sign(sk, m1))
        s2 = PointG2.from_bytes(bls.sign(sk, m2))
        neg = -PointG1.generator()
        c0, p0 = hpairing.N_PRODUCT_CHECKS, hpairing.N_MILLER_PAIRS
        oks = hpairing.pairing_check_groups([
            [(neg, s1), (pub, hash_to_g2(m1))],
            [(neg, s2), (pub, hash_to_g2(m2))],
            [(neg, s1), (pub, hash_to_g2(m2))],   # mismatched: False
            [],                                   # vacuous: True
        ])
        assert oks == [True, True, False, True]
        assert hpairing.N_PRODUCT_CHECKS - c0 == 1
        assert hpairing.N_MILLER_PAIRS - p0 == 6


# ---------------------------------------------------------------------------
# Device wire-RLC tier (CPU backend in the suite; compile-heavy)
# ---------------------------------------------------------------------------

@pytest.mark.device
class TestWireRLC:
    @pytest.fixture(scope="class")
    def engine(self):
        from drand_tpu.ops.engine import BatchedEngine

        eng = BatchedEngine(buckets=(4,), wire_prep=True)
        eng.rlc_min = 2
        return eng

    def test_all_valid_span_two_miller_pairs(self, engine, keys):
        """THE acceptance criterion: an all-valid span through wire_rlc
        dispatches exactly one pairing row = 2 Miller pairs (was 2N),
        even when the span chunks over multiple combine buckets."""
        from drand_tpu.ops import engine as eng_mod

        sk, pub = keys
        beacons = _make_chain(sk, 6)  # 6 checks over bucket 4: 2 chunks
        got = engine.verify_beacons_wire_rlc(pub, beacons)
        assert got is not None and got.all() and len(got) == 6
        assert engine._wire_rlc_ok.get(4) is True
        # warm: second span pays exactly one 2-pair product check
        c0, p0 = eng_mod.N_PRODUCT_CHECKS, eng_mod.N_MILLER_PAIRS
        got = engine.verify_beacons_wire_rlc(pub, beacons)
        assert got is not None and got.all()
        assert eng_mod.N_PRODUCT_CHECKS - c0 == 1
        assert eng_mod.N_MILLER_PAIRS - p0 == 2

    def test_malformed_lane_excluded_not_poisoning(self, engine, keys):
        """A malformed signature encoding is a per-item False and is
        masked out of the device combination — the rest of the span
        still verifies as one 2-pair row."""
        from drand_tpu.ops import engine as eng_mod

        sk, pub = keys
        beacons = _make_chain(sk, 6)
        beacons[3].signature = b"\x00" * 96
        c0, p0 = eng_mod.N_PRODUCT_CHECKS, eng_mod.N_MILLER_PAIRS
        got = engine.verify_beacons_wire_rlc(pub, beacons)
        assert got is not None
        assert list(got) == [True, True, True, False, True, True]
        assert eng_mod.N_MILLER_PAIRS - p0 == 2

    def test_bad_signature_false_reject_only_fallback(self, engine, keys):
        """A decodable-but-wrong signature fails the combined check: the
        tier returns None (false-reject-only) and the cascade lands on
        the per-item wire graph with exact verdicts."""
        sk, pub = keys
        beacons = _make_chain(sk, 4)
        beacons[2].signature = beacons[1].signature
        assert engine.verify_beacons_wire_rlc(pub, beacons) is None
        got = engine.verify_beacons(pub, beacons)
        assert list(got) == [True, True, False, True]

    def test_kat_gate_failure_forces_wire_fallback(self, engine, keys,
                                                   monkeypatch):
        """A combine graph that fails its KAT is disabled: the tier
        reports None and verify_beacons still answers exactly via the
        per-item wire graph."""
        sk, pub = keys
        beacons = _make_chain(sk, 4)

        def broken(*a, **k):
            raise RuntimeError("wire-rlc miscompile probe")

        monkeypatch.setattr(engine, "_wire_rlc_ok", {})
        monkeypatch.setattr(engine, "_wire_rlc_jit", broken)
        assert engine.verify_beacons_wire_rlc(pub, beacons) is None
        assert engine._wire_rlc_ok.get(4) is False  # gate latched
        got = engine.verify_beacons(pub, beacons)
        assert got.all() and len(got) == 4

    def test_escape_hatch_disables_wire_rlc(self, engine, monkeypatch):
        monkeypatch.setenv("DRAND_TPU_BATCH_VERIFY", "0")
        assert engine.wire_rlc_active(64) is False
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        assert engine.wire_rlc_active(64) is True
        assert engine.wire_rlc_active(1) is False  # under the floor

    def test_dispatch_times_wire_rlc_path(self, engine, keys, monkeypatch):
        """crypto/batch.py dispatches the tier under its own
        engine_op_seconds{path="wire_rlc"} label (check_metrics lints the
        label into the documented set)."""
        sk, pub = keys
        beacons = _make_chain(sk, 4)
        monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
        old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
        batch.configure("device", engine=engine)
        try:
            out = batch.verify_beacons(pub, beacons)
            assert out.all() and len(out) == 4
            # the first dispatch of a cold (op, wire_rlc, bucket) shape
            # lands in engine_compile_seconds (ISSUE 6 split); the shape
            # is warm now, so the next dispatch samples the path label
            h1 = _sample_count(metrics.REGISTRY, "engine_op_seconds",
                               op="verify_beacons", path="wire_rlc")
            out = batch.verify_beacons(pub, beacons)
            assert out.all() and len(out) == 4
            assert _sample_count(metrics.REGISTRY, "engine_op_seconds",
                                 op="verify_beacons",
                                 path="wire_rlc") == h1 + 1
        finally:
            batch._MODE, batch._MIN_BATCH, batch._ENGINE = old
