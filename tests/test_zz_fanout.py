"""Edge fan-out push tier (ISSUE 14): round-boundary SSE/NDJSON
streaming on /public/latest, explicit load shedding, SO_REUSEPORT
multi-process relay workers, and the packed segment chain store.

Late-alphabet filename per the tier-1 chunking convention
(tools/tier1_chunks.sh). Everything here is host-only — no pairings,
no device graphs, no backend init; the worker smoke test spawns real
CLI subprocesses on the wall clock (a few seconds, like the chaos
suite's socket scenarios).
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import aiohttp
import pytest

from conftest import sample_count
from drand_tpu import metrics
from drand_tpu.chain import time_math
from drand_tpu.chain.beacon import Beacon
from drand_tpu.chain.info import Info
from drand_tpu.chain.segments import SegmentStore, migrate_store
from drand_tpu.chain.store import SQLiteStore, StoreError
from drand_tpu.client.interface import Client, ClientError, Result
from drand_tpu.crypto.curves import PointG1
from drand_tpu.http_server import fanout
from drand_tpu.http_server.server import PublicServer
from drand_tpu.utils.clock import FakeClock

PERIOD = 5
GENESIS = 1_700_000_000
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SSE = {"Accept": "text/event-stream"}
NDJSON = {"Accept": "application/x-ndjson"}


class ScriptedUpstream(Client):
    """Deterministic /public/latest upstream on the FakeClock: yields
    one synthetic beacon per round boundary; ``dead=True`` makes every
    call fail (the degraded-upstream scenarios)."""

    def __init__(self, clock):
        self.clock = clock
        self.dead = False
        self.latest: Result | None = None

    async def info(self) -> Info:
        if self.dead:
            raise ClientError("upstream dead")
        return Info(public_key=PointG1.generator(), period=PERIOD,
                    genesis_time=GENESIS, genesis_seed=b"s" * 32,
                    group_hash=b"g" * 32)

    async def get(self, round_no: int = 0) -> Result:
        if self.dead:
            raise ClientError("upstream dead")
        if round_no == 0 and self.latest is not None:
            return self.latest
        raise ClientError("round not available")

    async def watch(self):
        while True:
            if self.dead:
                raise ClientError("upstream dead")
            now = self.clock.now()
            next_r, next_t = time_math.next_round(int(now), PERIOD,
                                                  GENESIS)
            await self.clock.sleep(max(0.0, next_t - now))
            if self.dead:
                raise ClientError("upstream dead")
            r = next_r - 1
            self.latest = Result(round=r,
                                 signature=bytes([r % 251]) * 96)
            yield self.latest


async def _start(clock, client, **kw):
    server = PublicServer(client, clock=clock, **kw)
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    await clock.settle()
    return server, f"http://127.0.0.1:{port}"


async def _read_sse_event(resp, timeout=5.0):
    """One SSE frame -> (round id, payload dict)."""
    rid, data = None, None
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = await asyncio.wait_for(resp.content.readline(), timeout)
        if line == b"\n" and data is not None:
            return rid, data
        if line.startswith(b"id: "):
            rid = int(line[4:].strip())
        elif line.startswith(b"data: "):
            data = json.loads(line[6:])
    raise TimeoutError("no complete SSE frame")


# ---------------------------------------------------------------------------
# tentpole: one hub publish fans a round out to every stream watcher
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_sse_and_ndjson_fanout_one_wakeup_per_round():
    """N watchers across BOTH stream protocols all receive round N+1
    from ONE hub publish: the per-proto wakeup counter moves by exactly
    1 per round while every watcher sees the beacon — the
    not-O(watchers) cost model, asserted at the meter."""
    clock = FakeClock(start=GENESIS + 1)
    upstream = ScriptedUpstream(clock)
    server, url = await _start(clock, upstream)
    sess = aiohttp.ClientSession()
    try:
        sse = [await sess.get(url + "/public/latest", headers=SSE)
               for _ in range(3)]
        nd = [await sess.get(url + "/public/latest", headers=NDJSON)
              for _ in range(2)]
        assert all(r.status == 200 for r in sse + nd)
        assert metrics.RELAY_WATCHERS._value.get() == 5
        wake_sse = sample_count(metrics.HTTP_REGISTRY,
                                "relay_wakeups", proto="sse")
        wake_nd = sample_count(metrics.HTTP_REGISTRY,
                               "relay_wakeups", proto="ndjson")

        await clock.advance(PERIOD)
        for resp in sse:
            rid, d = await _read_sse_event(resp)
            assert rid == 1 and d["round"] == 1
        for resp in nd:
            line = await asyncio.wait_for(resp.content.readline(), 5)
            assert json.loads(line)["round"] == 1

        assert sample_count(metrics.HTTP_REGISTRY, "relay_wakeups",
                            proto="sse") == wake_sse + 1
        assert sample_count(metrics.HTTP_REGISTRY, "relay_wakeups",
                            proto="ndjson") == wake_nd + 1

        # second round: another single publish per proto
        await clock.advance(PERIOD)
        for resp in sse:
            rid, d = await _read_sse_event(resp)
            assert rid == 2 and d["round"] == 2
        for resp in nd:
            line = await asyncio.wait_for(resp.content.readline(), 5)
            assert json.loads(line)["round"] == 2
        assert sample_count(metrics.HTTP_REGISTRY, "relay_wakeups",
                            proto="sse") == wake_sse + 2
        assert sample_count(metrics.HTTP_REGISTRY, "relay_wakeups",
                            proto="ndjson") == wake_nd + 2
        for resp in sse + nd:
            resp.close()
    finally:
        await sess.close()
        await server.stop()


@pytest.mark.asyncio
async def test_slow_consumer_disconnected_at_queue_bound():
    """A subscriber whose bounded queue fills is DISCONNECTED (drain +
    sentinel), counted on relay_shed_total{reason=slow_consumer} —
    never buffered unboundedly. A healthy subscriber in the same hub
    keeps its stream."""
    hub = fanout.FanoutHub(queue_max=2)
    slow = hub.subscribe(fanout.PROTO_SSE)
    healthy = hub.subscribe(fanout.PROTO_NDJSON)
    shed0 = sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                         reason="slow_consumer")
    reached = []
    for r in range(1, 5):
        reached.append(hub.publish({"round": r}, r))
        # the healthy consumer drains every round, the slow one never
        rnd, frame = await asyncio.wait_for(healthy.next(), 1)
        assert rnd == r and json.loads(frame)["round"] == r
    # rounds 1,2 queued for slow; round 3's publish shed it
    assert slow.shed
    assert hub.watcher_count() == 1
    assert sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                        reason="slow_consumer") == shed0 + 1
    # the slow consumer's next read is the close sentinel, immediately
    assert await asyncio.wait_for(slow.next(), 1) is None
    # reached counts dropped from 2 subscribers to 1
    assert reached[0] == 2 and reached[-1] == 1
    hub.close_all()
    assert await asyncio.wait_for(healthy.next(), 1) is None


@pytest.mark.asyncio
async def test_shed_429_retry_after_lands_on_next_boundary():
    """Above the watcher cap the server sheds BEFORE handler work: 429
    with Retry-After aligned to the next round boundary (FakeClock
    exact), relay_shed_total{reason=watcher_cap} counted; a slot
    freeing up re-admits new watchers."""
    clock = FakeClock(start=GENESIS + 1)
    upstream = ScriptedUpstream(clock)
    server, url = await _start(clock, upstream, max_watchers=1)
    sess = aiohttp.ClientSession()
    try:
        held = await sess.get(url + "/public/latest", headers=SSE)
        assert held.status == 200
        # advance into the middle of a round so the boundary math is
        # non-trivial: now = genesis+1+7 -> next boundary at +10s
        await clock.advance(2)
        shed0 = sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                             reason="watcher_cap")
        resp = await sess.get(url + "/public/latest", headers=NDJSON)
        assert resp.status == 429
        now = clock.now()
        _, next_t = time_math.next_round(int(now), PERIOD, GENESIS)
        assert resp.headers["Retry-After"] == str(int(next_t - now))
        assert sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                            reason="watcher_cap") == shed0 + 1
        # plain GET pollers are never shed by the watcher cap
        poll = await sess.get(url + "/public/latest")
        assert poll.status in (200, 404)  # no beacon yet is fine
        # free the slot -> a new stream is admitted. Disconnects are
        # detected at the next write (bounded by one round period):
        # advance a boundary so the publish hits the closed socket.
        held.close()
        await asyncio.sleep(0.05)
        await clock.advance(PERIOD)
        for _ in range(100):
            if metrics.RELAY_WATCHERS._value.get() == 0:
                break
            await asyncio.sleep(0.05)
        assert metrics.RELAY_WATCHERS._value.get() == 0
        again = await sess.get(url + "/public/latest", headers=SSE)
        assert again.status == 200
        again.close()
    finally:
        await sess.close()
        await server.stop()


@pytest.mark.asyncio
async def test_stale_upstream_preserved_on_streams_and_polls():
    """Upstream dies: a NEW stream watcher still connects (200) with
    X-Drand-Stale carrying the lag and the last-known beacon as its
    snapshot frame; the plain-GET degraded path keeps no-store and
    never carries an ETag. The watch loop's restart rides the retry
    policy (net_retry_attempts_total{op=watch} moves) and recovery
    resumes the push stream."""
    clock = FakeClock(start=GENESIS + 1)
    upstream = ScriptedUpstream(clock)
    server, url = await _start(clock, upstream)
    sess = aiohttp.ClientSession()
    try:
        await clock.advance(PERIOD)  # round 1 published, info cached
        upstream.dead = True
        retries0 = sample_count(metrics.GROUP_REGISTRY,
                                "net_retry_attempts", op="watch")
        await clock.advance(PERIOD * 3)
        # streams: connect DURING the outage
        resp = await sess.get(url + "/public/latest", headers=SSE)
        assert resp.status == 200
        assert int(resp.headers["X-Drand-Stale"]) >= 2
        rid, d = await _read_sse_event(resp)
        assert rid == 1 and d["round"] == 1  # last-known snapshot
        # plain GET: stale 200, no-store, NO ETag on the degraded path
        poll = await sess.get(url + "/public/latest")
        assert poll.status == 200
        assert int(poll.headers["X-Drand-Stale"]) >= 2
        assert poll.headers["Cache-Control"] == "no-store"
        assert "ETag" not in poll.headers
        # the restart loop is riding the policy, not a raw sleep
        assert sample_count(metrics.GROUP_REGISTRY,
                            "net_retry_attempts", op="watch") > retries0
        # recovery: the stream watcher resumes at the next boundary
        upstream.dead = False
        await clock.advance(PERIOD * 4)
        rid, d = await _read_sse_event(resp)
        assert d["round"] > 1
        resp.close()
    finally:
        await sess.close()
        await server.stop()


@pytest.mark.asyncio
async def test_latest_etag_304_for_pollers():
    """Non-stream GET /public/latest: round-keyed ETag +
    If-None-Match -> 304 (a poller between rounds costs a header, not
    a body); the ETag rolls with the round."""
    clock = FakeClock(start=GENESIS + 1)
    upstream = ScriptedUpstream(clock)
    server, url = await _start(clock, upstream)
    sess = aiohttp.ClientSession()
    try:
        await clock.advance(PERIOD)
        r1 = await sess.get(url + "/public/latest")
        assert r1.status == 200
        etag = r1.headers["ETag"]
        assert etag == '"r1"'
        assert r1.headers["Cache-Control"] == "no-cache"
        r304 = await sess.get(url + "/public/latest",
                              headers={"If-None-Match": etag})
        assert r304.status == 304
        assert r304.headers["ETag"] == etag
        assert await r304.read() == b""
        # stale validator after the round advances -> fresh 200
        await clock.advance(PERIOD)
        r2 = await sess.get(url + "/public/latest",
                            headers={"If-None-Match": etag})
        assert r2.status == 200
        assert r2.headers["ETag"] == '"r2"'
    finally:
        await sess.close()
        await server.stop()


# ---------------------------------------------------------------------------
# segment storage
# ---------------------------------------------------------------------------


def _chain(n, v2_every=2):
    out, prev = [], b""
    for r in range(n):
        sig = b"seed" * 8 if r == 0 else bytes([r % 251]) * 96
        out.append(Beacon(
            round=r, previous_sig=prev, signature=sig,
            signature_v2=(b"v" * 96 if r and r % v2_every == 0 else b"")))
        prev = sig
    return out


def test_segment_store_roundtrip_and_depth(tmp_path):
    """Field-exact round-trip (genesis empty prev, v2 present/absent),
    O(1) get at million-round depth, cursor_from across a segment
    boundary and across holes, del_round, len, reopen persistence, and
    the oversize-field guard."""
    store = SegmentStore(str(tmp_path / "segments"))
    beacons = _chain(20)
    for b in beacons:
        store.put(b)
    for b in beacons:
        assert store.get(b.round).equal(b)
    assert store.get(10_000) is None and store.get(-1) is None
    assert len(store) == 20 and store.last().round == 19

    # depth: a record a million rounds out is a seek, not a scan —
    # and it lands in a different segment file
    deep = Beacon(round=1_000_000, previous_sig=b"p" * 96,
                  signature=b"q" * 96, signature_v2=b"r" * 96)
    store.put(deep)
    t0 = time.perf_counter()
    assert store.get(1_000_000).equal(deep)
    assert time.perf_counter() - t0 < 0.1
    assert store.last().round == 1_000_000
    # cursor across the hole: 19 -> 1_000_000 directly
    assert [b.round for b in store.cursor_from(15)] == \
        [15, 16, 17, 18, 19, 1_000_000]

    # segment-boundary crossing (default segment = 65536 rounds)
    for r in range(65_534, 65_539):
        store.put(Beacon(round=r, previous_sig=b"x" * 96,
                         signature=bytes([r % 251]) * 96))
    assert [b.round for b in store.cursor_from(65_534)] == \
        [65_534, 65_535, 65_536, 65_537, 65_538, 1_000_000]

    store.del_round(1_000_000)
    assert store.get(1_000_000) is None
    assert store.last().round == 65_538

    # del_from rollback (`util del-beacon` on a segment chain): the
    # partial segment truncates, whole segments past the cut vanish
    assert store.del_from(65_536) == 3
    assert store.last().round == 65_535
    assert store.get(65_537) is None
    assert [b.round for b in store.cursor_from(65_530)] == \
        [65_534, 65_535]

    reads0 = sample_count(metrics.GROUP_REGISTRY,
                          "chain_store_reads", backend="segment")
    assert store.get(2) is not None
    assert sample_count(metrics.GROUP_REGISTRY, "chain_store_reads",
                        backend="segment") == reads0 + 1

    store.close()
    reopened = SegmentStore(str(tmp_path / "segments"))
    assert reopened.last().round == 65_535
    assert [b.round for b in reopened.cursor_from(60_000)] == \
        [65_534, 65_535]
    assert reopened.get(7).equal(beacons[7])

    with pytest.raises(StoreError):
        reopened.put(Beacon(round=5, signature=b"z" * 97))
    reopened.close()


def test_store_migrate_equivalence_vs_sqlite(tmp_path, capsys):
    """`drand-tpu util store-migrate` converts a SQLite chain to the
    segment format (and back) with byte-exact beacon equality at every
    round; the AppendStore-visible surface (last/get/cursor) agrees."""
    from drand_tpu.cli.__main__ import main as cli_main

    db = str(tmp_path / "chain.db")
    sq = SQLiteStore(db)
    beacons = _chain(40, v2_every=3)
    for b in beacons:
        sq.put(b)
    sq.close()

    cli_main(["util", "store-migrate", "--db", db,
              "--out", str(tmp_path / "segments")])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["migrated"] == 40

    seg = SegmentStore(str(tmp_path / "segments"))
    sq = SQLiteStore(db)
    pairs = list(zip(sq.cursor(), seg.cursor()))
    assert len(pairs) == 40
    assert all(a.equal(b) for a, b in pairs)
    assert seg.last().equal(sq.last())
    sq.close()
    seg.close()

    # reverse: segment -> fresh sqlite, still byte-exact
    db2 = str(tmp_path / "chain2.db")
    cli_main(["util", "store-migrate", "--db", db2,
              "--out", str(tmp_path / "segments"), "--reverse"])
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["migrated"] == 40
    back = SQLiteStore(db2)
    assert all(a.equal(b) for a, b in zip(beacons, back.cursor()))
    back.close()


# ---------------------------------------------------------------------------
# SO_REUSEPORT worker smoke
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _sub_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    env.pop("JAX_PLATFORMS", None)  # workers never touch the backend
    return env


class _StubOrigin:
    """Wall-clock origin for the worker subprocesses: one-second
    period, REAL BLS-signed chained beacons (the relay's verifying
    client stack checks every signature even with --insecure — that
    flag only waives the chain-hash trust pin)."""

    def __init__(self):
        import hashlib

        from drand_tpu.chain.beacon import message
        from drand_tpu.crypto import bls

        self.period = 1
        self.genesis = int(time.time()) - 3
        self.sk, self.pub = bls.keygen(b"zz-fanout-origin-seed-0123456789")
        self._sigs = {0: b"zz-fanout-genesis-seed-0123456789"}
        self._sign = lambda r, prev: bls.sign(self.sk, message(r, prev))
        self._sha = hashlib.sha256

    def _sig(self, r):
        if r not in self._sigs:
            self._sigs[r] = self._sign(r, self._sig(r - 1))
        return self._sigs[r]

    def _beacon(self, r):
        sig = self._sig(r)
        return {"round": r, "signature": sig.hex(),
                "previous_signature": self._sig(r - 1).hex(),
                "randomness": self._sha(sig).hexdigest()}

    async def start(self):
        from aiohttp import web

        async def info(request):
            return web.json_response({
                "public_key": self.pub.to_bytes().hex(),
                "period": self.period, "genesis_time": self.genesis,
                "group_hash": "67" * 32, "hash": "67" * 32})

        async def latest(request):
            r = time_math.current_round(int(time.time()), self.period,
                                        self.genesis)
            return web.json_response(self._beacon(r))

        async def by_round(request):
            r = int(request.match_info["round"])
            cur = time_math.current_round(int(time.time()), self.period,
                                          self.genesis)
            if r > cur:
                return web.json_response({"error": "not yet"}, status=404)
            return web.json_response(self._beacon(r))

        app = web.Application()
        app.add_routes([web.get("/info", info),
                        web.get("/public/latest", latest),
                        web.get("/public/{round}", by_round)])
        self.runner = web.AppRunner(app)
        await self.runner.setup()
        site = web.TCPSite(self.runner, "127.0.0.1", 0)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return f"http://127.0.0.1:{self.port}"


def test_reuseport_worker_smoke():
    """`relay --workers 2`: both workers accept on ONE port via
    SO_REUSEPORT; killing one worker leaves the survivor's watchers
    streaming undisturbed; SIGTERM drains the group gracefully."""

    async def run():
        origin = _StubOrigin()
        origin_url = await origin.start()
        port = _free_port()
        parent = subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "relay",
             "--url", origin_url, "--listen", f"127.0.0.1:{port}",
             "--insecure", "--workers", "2"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=_sub_env(), cwd=REPO)
        url = f"http://127.0.0.1:{port}"
        sess = aiohttp.ClientSession()
        streams = []  # (worker pid, response)
        try:
            # wait for the shared port to accept
            deadline = time.time() + 30
            while time.time() < deadline:
                try:
                    resp = await sess.get(url + "/public/latest",
                                          headers=SSE)
                    if resp.status == 200:
                        streams.append(
                            (int(resp.headers["X-Drand-Worker"]), resp))
                        break
                    resp.close()
                except aiohttp.ClientError:
                    pass
                await asyncio.sleep(0.3)
            assert streams, "relay workers never came up"
            # connect until BOTH workers hold at least one stream (the
            # kernel hashes connections; a couple dozen tries suffice)
            for _ in range(40):
                if len({pid for pid, _ in streams}) >= 2:
                    break
                resp = await sess.get(url + "/public/latest", headers=SSE)
                assert resp.status == 200
                streams.append(
                    (int(resp.headers["X-Drand-Worker"]), resp))
            pids = {pid for pid, _ in streams}
            assert len(pids) == 2, f"only saw workers {pids}"

            victim = min(pids)
            survivor = max(pids)
            os.kill(victim, signal.SIGKILL)
            # watchers on the SURVIVOR keep receiving rounds
            surv_resp = next(r for pid, r in streams if pid == survivor)
            rid, d = await _read_sse_event(surv_resp, timeout=10)
            assert d["round"] >= 1
            rid2, _ = await _read_sse_event(surv_resp, timeout=10)
            assert rid2 > rid  # still advancing after the kill
            # new connections land on the survivor (the dead worker's
            # socket is gone from the reuseport group); retry a couple
            # of times — connections parked in the dead worker's accept
            # queue at kill time are lost, not redistributed
            fresh = None
            for _ in range(5):
                try:
                    fresh = await asyncio.wait_for(
                        sess.get(url + "/public/latest", headers=SSE), 5)
                    break
                except (aiohttp.ClientError, asyncio.TimeoutError):
                    await asyncio.sleep(0.3)
            assert fresh is not None and fresh.status == 200
            assert int(fresh.headers["X-Drand-Worker"]) == survivor
            fresh.close()
            # graceful drain: SIGTERM the parent; the survivor ends the
            # stream cleanly. The parent exits 1, not 0: the SIGKILLed
            # worker is a crash and must surface to any supervisor
            parent.send_signal(signal.SIGTERM)
            end = await asyncio.wait_for(surv_resp.content.read(), 15)
            assert isinstance(end, bytes)  # stream ended, not reset
            assert parent.wait(timeout=15) == 1
        finally:
            for _, r in streams:
                r.close()
            await sess.close()
            await origin.runner.cleanup()
            if parent.poll() is None:
                parent.kill()
                parent.wait(timeout=10)

    asyncio.run(run())
