"""Scheme-layer tests: threshold BLS, polynomials, auth sigs, Schnorr,
ECIES, timelock — reproducing the reference's crypto API surface
(SURVEY.md §2.2)."""

import hashlib

import pytest

from drand_tpu.crypto import bls, ecies, schnorr, tbls, timelock
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.poly import (
    PriPoly,
    PriShare,
    PubShare,
    lagrange_coefficients,
    minimum_threshold,
    recover_commit,
    recover_secret,
)

N, T = 5, 3
MSG = hashlib.sha256(b"beacon round 1").digest()


@pytest.fixture(scope="module")
def dkg_setup():
    """Synthesized shares, bypassing the DKG — the BeaconTest trick
    (reference: chain/beacon/node_test.go:52-104 dkgShares)."""
    poly = PriPoly.random(T, seed=b"test-dkg")
    shares = poly.shares(N)
    pub_poly = poly.commit()
    return poly, shares, pub_poly


class TestPoly:
    def test_secret_recovery(self, dkg_setup):
        poly, shares, _ = dkg_setup
        assert recover_secret(shares[:T], T) == poly.secret()
        assert recover_secret(shares[2:], T) == poly.secret()
        with pytest.raises(ValueError):
            recover_secret(shares[:T - 1], T)

    def test_any_t_subset_recovers(self, dkg_setup):
        poly, shares, _ = dkg_setup
        import itertools

        for combo in itertools.combinations(shares, T):
            assert recover_secret(list(combo), T) == poly.secret()

    def test_pub_poly_eval_matches_pri(self, dkg_setup):
        _, shares, pub_poly = dkg_setup
        for s in shares:
            assert pub_poly.eval(s.index).value == PointG1.generator().mul(s.value)

    def test_commit_is_public_key(self, dkg_setup):
        poly, _, pub_poly = dkg_setup
        assert pub_poly.commit() == PointG1.generator().mul(poly.secret())

    def test_lagrange_sums_to_one_weighted(self):
        # interpolating the constant polynomial: coefficients sum to 1
        lambdas = lagrange_coefficients([0, 2, 4])
        from drand_tpu.crypto.fields import R

        assert sum(lambdas.values()) % R == 1

    def test_poly_add(self):
        a, b = PriPoly.random(T, seed=b"a"), PriPoly.random(T, seed=b"b")
        s = a.add(b)
        from drand_tpu.crypto.fields import R

        assert s.secret() == (a.secret() + b.secret()) % R
        assert a.commit().add(b.commit()).commit() == s.commit().commit()

    def test_minimum_threshold(self):
        assert minimum_threshold(4) == 3
        assert minimum_threshold(5) == 3
        assert minimum_threshold(10) == 6  # League of Entropy: 6-of-10


class TestTBLS:
    def test_partial_roundtrip(self, dkg_setup):
        _, shares, pub_poly = dkg_setup
        partial = tbls.sign_partial(shares[1], MSG)
        assert len(partial) == tbls.PARTIAL_SIG_SIZE
        assert tbls.index_of(partial) == 1
        assert tbls.verify_partial(pub_poly, MSG, partial)

    def test_partial_wrong_msg_or_index(self, dkg_setup):
        _, shares, pub_poly = dkg_setup
        partial = tbls.sign_partial(shares[1], MSG)
        assert not tbls.verify_partial(pub_poly, b"other", partial)
        # re-prefix with a wrong index: points at another node's pubkey share
        forged = (2).to_bytes(2, "big") + partial[2:]
        assert not tbls.verify_partial(pub_poly, MSG, forged)

    def test_recover_and_verify(self, dkg_setup):
        poly, shares, pub_poly = dkg_setup
        partials = [tbls.sign_partial(s, MSG) for s in shares[:T]]
        sig = tbls.recover(pub_poly, MSG, partials, T, N)
        assert len(sig) == tbls.SIG_SIZE
        assert tbls.verify_recovered(pub_poly.commit(), MSG, sig)
        # recovered signature is the unique sk*H(m): any t-subset agrees
        partials2 = [tbls.sign_partial(s, MSG) for s in shares[2:]]
        assert tbls.recover(pub_poly, MSG, partials2, T, N) == sig
        # and equals a direct signature under the (never-assembled) secret
        direct = bls.sign(poly.secret(), MSG)
        assert direct == sig

    def test_recover_skips_garbage(self, dkg_setup):
        _, shares, pub_poly = dkg_setup
        partials = [b"\x00\x01garbage", tbls.sign_partial(shares[0], MSG)]
        partials += [tbls.sign_partial(s, MSG) for s in shares[1:T]]
        sig = tbls.recover(pub_poly, MSG, partials, T, N)
        assert tbls.verify_recovered(pub_poly.commit(), MSG, sig)

    def test_recover_insufficient(self, dkg_setup):
        _, shares, pub_poly = dkg_setup
        partials = [tbls.sign_partial(s, MSG) for s in shares[: T - 1]]
        with pytest.raises(ValueError):
            tbls.recover(pub_poly, MSG, partials, T, N)

    def test_recover_commit_on_g2(self, dkg_setup):
        poly, shares, _ = dkg_setup
        h = PointG2.generator()
        pshares = [PubShare(s.index, h.mul(s.value)) for s in shares[:T]]
        assert recover_commit(pshares, T) == h.mul(poly.secret())


class TestBLSAuth:
    def test_sign_verify(self):
        sk, pub = bls.keygen(seed=b"auth")
        sig = bls.sign(sk, b"identity hash")
        assert bls.verify(pub, b"identity hash", sig)
        assert not bls.verify(pub, b"other", sig)
        sk2, pub2 = bls.keygen(seed=b"auth2")
        assert not bls.verify(pub2, b"identity hash", sig)

    def test_malformed_sig(self):
        _, pub = bls.keygen(seed=b"auth")
        assert not bls.verify(pub, b"m", b"\x00" * 96)
        assert not bls.verify(pub, b"m", b"short")
        assert not bls.verify(pub, b"m", PointG2.infinity().to_bytes())


class TestSchnorr:
    def test_sign_verify(self):
        sk, pub = bls.keygen(seed=b"schnorr")
        sig = schnorr.sign(sk, b"dkg packet")
        assert len(sig) == schnorr.SIG_SIZE
        assert schnorr.verify(pub, b"dkg packet", sig)
        assert not schnorr.verify(pub, b"tampered", sig)
        _, pub2 = bls.keygen(seed=b"schnorr2")
        assert not schnorr.verify(pub2, b"dkg packet", sig)

    def test_deterministic(self):
        sk, _ = bls.keygen(seed=b"schnorr")
        assert schnorr.sign(sk, b"m") == schnorr.sign(sk, b"m")

    def test_malformed(self):
        _, pub = bls.keygen(seed=b"schnorr")
        assert not schnorr.verify(pub, b"m", b"\x00" * schnorr.SIG_SIZE)
        assert not schnorr.verify(pub, b"m", b"")


class TestECIES:
    def test_roundtrip(self):
        sk, pub = bls.keygen(seed=b"ecies")
        ct = ecies.encrypt(pub, b"private randomness 1234")
        assert ecies.decrypt(sk, ct) == b"private randomness 1234"

    def test_tamper_detected(self):
        sk, pub = bls.keygen(seed=b"ecies")
        ct = bytearray(ecies.encrypt(pub, b"secret"))
        ct[-1] ^= 1
        with pytest.raises(ValueError):
            ecies.decrypt(sk, bytes(ct))

    def test_wrong_key(self):
        sk, pub = bls.keygen(seed=b"ecies")
        sk2, _ = bls.keygen(seed=b"ecies-other")
        ct = ecies.encrypt(pub, b"secret")
        with pytest.raises(ValueError):
            ecies.decrypt(sk2, ct)

    def test_nondeterministic_ciphertexts(self):
        _, pub = bls.keygen(seed=b"ecies")
        assert ecies.encrypt(pub, b"m") != ecies.encrypt(pub, b"m")


class TestTimelock:
    """The fork's headline capability: encrypt-to-future-round
    (reference: core/timelock_test.go:17-72)."""

    def test_roundtrip_via_beacon_sig(self):
        # network master key
        sk, pub = bls.keygen(seed=b"timelock-master")
        round_no = 1337
        identity = hashlib.sha256(round_no.to_bytes(8, "big")).digest()  # MessageV2
        ct = timelock.encrypt(pub, identity, b"to the future")
        # ... later, round 1337's V2 signature is published:
        sig_v2 = bls.sign(sk, identity)
        assert timelock.decrypt(sig_v2, ct) == b"to the future"

    def test_wrong_round_sig_fails(self):
        sk, pub = bls.keygen(seed=b"timelock-master")
        identity = hashlib.sha256((1).to_bytes(8, "big")).digest()
        ct = timelock.encrypt(pub, identity, b"msg")
        wrong_sig = bls.sign(sk, hashlib.sha256((2).to_bytes(8, "big")).digest())
        with pytest.raises(ValueError):
            timelock.decrypt(wrong_sig, ct)

    def test_tampered_ciphertext_fails(self):
        sk, pub = bls.keygen(seed=b"timelock-master")
        identity = b"round-id"
        ct = timelock.encrypt(pub, identity, b"msg12345")
        bad = timelock.Ciphertext(ct.u, ct.v, bytes(len(ct.w)))
        with pytest.raises(ValueError):
            timelock.decrypt(bls.sign(sk, identity), bad)

    def test_serialization(self):
        _, pub = bls.keygen(seed=b"timelock-master")
        ct = timelock.encrypt(pub, b"id", b"hello")
        rt = timelock.Ciphertext.from_bytes(ct.to_bytes())
        assert rt == ct
