"""Key/group file persistence (reference key/store.go) and fs perms."""

import os
import stat

import pytest

from drand_tpu.key.group import Group
from drand_tpu.key.keys import Node, new_key_pair
from drand_tpu.key.store import FileStore, KeyStoreError
from drand_tpu.testing.harness import synthesize_shares
from drand_tpu.utils import entropy, fs


def test_keypair_roundtrip(tmp_path):
    store = FileStore(str(tmp_path / "drand"))
    pair = new_key_pair("node-a.test:8080", seed=b"store-test")
    store.save_key_pair(pair)
    loaded = store.load_key_pair()
    assert loaded.key == pair.key
    assert loaded.public.equal(pair.public)
    assert loaded.public.valid_signature()
    # key files are 0600 inside 0700 folders
    mode = stat.S_IMODE(os.stat(store.private_key_file).st_mode)
    assert mode == 0o600
    kmode = stat.S_IMODE(os.stat(store.key_folder).st_mode)
    assert kmode == 0o700


def test_share_roundtrip(tmp_path):
    store = FileStore(str(tmp_path / "drand"))
    shares, _ = synthesize_shares(3, 2, seed=b"share-store")
    store.save_share(shares[1])
    loaded = store.load_share()
    assert loaded.pri_share == shares[1].pri_share
    assert loaded.commits == shares[1].commits


def test_group_roundtrip(tmp_path):
    store = FileStore(str(tmp_path / "drand"))
    pairs = [new_key_pair(f"n{i}.test:90{i:02d}", seed=b"grp%d" % i)
             for i in range(4)]
    shares, dist = synthesize_shares(4, 3, seed=b"group-store")
    group = Group(
        nodes=[Node(identity=p.public, index=i) for i, p in enumerate(pairs)],
        threshold=3, period=30, genesis_time=1_700_000_100,
        public_key=dist,
    )
    group.get_genesis_seed()
    store.save_group(group)
    loaded = store.load_group()
    assert loaded.hash() == group.hash()
    assert loaded.genesis_seed == group.genesis_seed
    assert loaded.public_key.equal(group.public_key)
    assert store.load_dist_public().equal(dist)


def test_missing_files_raise(tmp_path):
    store = FileStore(str(tmp_path / "drand"))
    assert not store.has_key_pair() and not store.has_share()
    with pytest.raises(KeyStoreError):
        store.load_key_pair()


def test_secure_folder_rejects_loose_perms(tmp_path):
    loose = tmp_path / "loose"
    loose.mkdir()
    os.chmod(loose, 0o755)
    with pytest.raises(PermissionError):
        fs.create_secure_folder(str(loose))


def test_entropy_mixing():
    a = entropy.get_random(32)
    b = entropy.get_random(32)
    assert a != b and len(a) == 32
    # script output is mixed, not used raw
    mixed = entropy.get_random(16, script="/bin/pwd")
    assert len(mixed) == 16
