"""Chain-health SLOs, OTLP span export, engine introspection (ISSUE 6).

Late-alphabet filename on purpose: tier-1 on the 1-core box runs in
chunks (tools/tier1_chunks.sh) and the capped single invocation keeps
its early-dot throughput when newer suites sort last (ROADMAP
operational constraint). Everything here is host-only crypto — no
device graphs, no fresh XLA compiles.
"""

import asyncio
import os
import threading

import aiohttp
import pytest
from aiohttp import web
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.chain.beacon import Beacon, message
from drand_tpu.client.direct import DirectClient
from drand_tpu.crypto import batch, bls
from drand_tpu.http_server.debug import add_trace_routes
from drand_tpu.http_server.server import PublicServer
from drand_tpu.obs import export as obs_export
from drand_tpu.obs import trace
from drand_tpu.obs.health import HEALTH, HealthState
from drand_tpu.obs.state import reset_observability
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5


def _make_chain(sk, n):
    prev, out = b"\x42" * 32, []
    for rnd in range(1, n + 1):
        sig = bls.sign(sk, message(rnd, prev))
        out.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig
    return out


async def _get(port, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}") as r:
            try:
                body = await r.json()
            except Exception:  # noqa: BLE001 — non-JSON error bodies
                body = {}
            return r.status, body


# ---------------------------------------------------------------------------
# healthz / readyz / lateness / SLO / OTLP store-flush (one harness run)
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_healthz_readyz_transitions(monkeypatch, tmp_path):
    """Live rounds -> /healthz ok + /readyz ready + lateness samples;
    a stalled chain (nodes stopped, clock running) -> 503 lagging,
    head-lag gauge up, missed-round counter incremented; the stored
    rounds' timelines land in the OTLP spool as resourceSpans."""
    spool = str(tmp_path / "otlp.ndjson")
    monkeypatch.setenv("DRAND_TPU_OTLP_SPOOL", spool)
    monkeypatch.delenv("DRAND_TPU_OTLP_ENDPOINT", raising=False)
    obs_export.reset_exporter()
    reset_observability()
    lat0 = _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_round_lateness_seconds")
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(2):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, 2)
    server = PublicServer(DirectClient(net.nodes[0].handler),
                          clock=net.clock)
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    try:
        status, body = await _get(port, "/healthz")
        assert status == 200 and body["status"] == "ok"
        assert body["head_round"] >= 2
        assert body["lag_rounds"] <= body["max_lag"]
        assert 0.0 <= body["slo_late_fraction"] <= 1.0
        status, body = await _get(port, "/readyz")
        assert status == 200 and body["ready"] is True
        # fake clock: rounds land on the boundary -> lateness samples
        # exist and the SLO window saw no late rounds
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_round_lateness_seconds") > lat0
        assert metrics.SLO_LATE_FRACTION._value.get() == 0.0

        # ---- stall: every node stops, wall clock keeps moving --------
        net.stop_all()
        missed0 = _sample_count(metrics.GROUP_REGISTRY,
                                "beacon_rounds_missed")
        await net.clock.advance(PERIOD * 10)
        status, body = await _get(port, "/healthz")
        assert status == 503 and body["status"] == "lagging"
        assert body["lag_rounds"] > body["max_lag"]
        assert metrics.CHAIN_HEAD_LAG._value.get() > 3
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_rounds_missed") > missed0
        status, body = await _get(port, "/readyz")
        assert status == 503 and body["ready"] is False
        assert "head lag" in body["reason"]
        # probing again at the same clock must not double-count misses
        again = _sample_count(metrics.GROUP_REGISTRY,
                              "beacon_rounds_missed")
        await _get(port, "/healthz")
        assert _sample_count(metrics.GROUP_REGISTRY,
                             "beacon_rounds_missed") == again
    finally:
        await server.stop()
        net.stop_all()

    # ---- OTLP spool: per-completed-round flush off the hot path -------
    docs = obs_export.read_spool(spool)
    assert docs, "no OTLP payloads spooled for the produced rounds"
    seed = net.group.get_genesis_seed()
    want = trace.round_trace_id(1, seed)
    spans_by_trace = {}
    for doc in docs:
        for rs in doc["resourceSpans"]:
            res_keys = {a["key"]: a["value"] for a in
                        rs["resource"]["attributes"]}
            assert res_keys["service.name"]["stringValue"] == "drand-tpu"
            for ss in rs["scopeSpans"]:
                for sp in ss["spans"]:
                    spans_by_trace.setdefault(sp["traceId"], []).append(sp)
    assert want in spans_by_trace
    names = {sp["name"] for sp in spans_by_trace[want]}
    assert "store" in names  # flushed AFTER the store span closed
    for sp in spans_by_trace[want]:
        assert int(sp["endTimeUnixNano"]) >= int(sp["startTimeUnixNano"])


# ---------------------------------------------------------------------------
# OTLP spool unit round-trip + bounded rotation
# ---------------------------------------------------------------------------

def test_otlp_spool_roundtrip_and_bounds(tmp_path):
    spool = str(tmp_path / "ring.ndjson")
    exp = obs_export.OTLPExporter(spool_path=spool,
                                  max_spool_bytes=8 * 1024)
    tr = trace.Tracer()
    with tr.activate(round_no=7, chain=b"chain-a"):
        with tr.span("partial", node="a", have=3):
            pass
        with tr.span("store", v2=True):
            pass
    rec = tr.get_trace(trace.round_trace_id(7, b"chain-a"))
    assert exp.export_round_sync(rec) == "spool"
    docs = obs_export.read_spool(spool)
    assert len(docs) == 1
    spans = docs[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert [s["name"] for s in spans] == ["partial", "store"]
    assert {s["spanId"] for s in spans} == \
        {s["span_id"] for s in rec["spans"]}
    assert all(s["traceId"] == rec["trace_id"] for s in spans)
    attrs = {a["key"]: a["value"] for a in spans[0]["attributes"]}
    assert attrs["node"]["stringValue"] == "a"
    assert attrs["have"]["intValue"] == "3"
    assert attrs["drand.round"]["intValue"] == "7"

    # bounded ring: many exports rotate instead of growing unbounded
    for r in range(200):
        with tr.activate(round_no=100 + r, chain=b"chain-a"):
            with tr.span("collect", i=r):
                pass
        exp.export_round_sync(
            tr.get_trace(trace.round_trace_id(100 + r, b"chain-a")))
    total = sum(os.path.getsize(p) for p in (spool, spool + ".1")
                if os.path.isfile(p))
    assert os.path.isfile(spool + ".1")
    assert total <= 2 * 8 * 1024 + 2048
    assert obs_export.read_spool(spool)  # both files still parse


@pytest.mark.asyncio
async def test_otlp_endpoint_post_and_session_reuse(tmp_path):
    """With an endpoint configured, rounds POST as OTLP/JSON to
    /v1/traces over ONE long-lived session (no per-round reconnect);
    a failing collector falls back to the spool."""
    posts = []

    async def collector(request):
        posts.append(await request.json())
        return web.json_response({})

    app = web.Application()
    app.add_routes([web.post("/v1/traces", collector)])
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    spool = str(tmp_path / "fallback.ndjson")
    exp = obs_export.OTLPExporter(endpoint=f"http://127.0.0.1:{port}",
                                  spool_path=spool)
    assert exp.endpoint.endswith("/v1/traces")
    tr = trace.Tracer()
    try:
        for r in (41, 42):
            with tr.activate(round_no=r, chain=b"post-chain"):
                with tr.span("recover"):
                    pass
            rec = tr.get_trace(trace.round_trace_id(r, b"post-chain"))
            assert await exp.export_round(rec) == "http"
        assert len(posts) == 2
        assert posts[0]["resourceSpans"][0]["scopeSpans"][0]["spans"]
        first_session = exp._session
        assert first_session is not None and not first_session.closed
        await runner.cleanup()  # collector gone: spool fallback
        with tr.activate(round_no=43, chain=b"post-chain"):
            with tr.span("recover"):
                pass
        rec = tr.get_trace(trace.round_trace_id(43, b"post-chain"))
        assert await exp.export_round(rec) == "spool"
        assert exp._session is first_session  # reused, not rebuilt
        assert len(obs_export.read_spool(spool)) == 1
    finally:
        if exp._session is not None and not exp._session.closed:
            await exp._session.close()
        await runner.cleanup()


def test_otlp_env_exporter_and_counter(monkeypatch, tmp_path):
    """note_round_complete with only the spool env set writes the spool
    synchronously outside a loop and counts under sink="spool"."""
    spool = str(tmp_path / "env.ndjson")
    monkeypatch.setenv("DRAND_TPU_OTLP_SPOOL", spool)
    monkeypatch.delenv("DRAND_TPU_OTLP_ENDPOINT", raising=False)
    obs_export.reset_exporter()
    try:
        with trace.TRACER.activate(round_no=31, chain=b"env-chain"):
            with trace.TRACER.span("recover"):
                pass
        c0 = _sample_count(metrics.REGISTRY, "otlp_export_rounds",
                           sink="spool")
        obs_export.note_round_complete(31, b"env-chain")
        assert _sample_count(metrics.REGISTRY, "otlp_export_rounds",
                             sink="spool") == c0 + 1
        docs = obs_export.read_spool(spool)
        assert docs and docs[0]["resourceSpans"]
        # a round the ring never saw is a clean no-op
        obs_export.note_round_complete(10**9, b"env-chain")
        assert len(obs_export.read_spool(spool)) == len(docs)
    finally:
        obs_export.reset_exporter()


# ---------------------------------------------------------------------------
# health unit behavior
# ---------------------------------------------------------------------------

def test_health_missed_rounds_counted_once():
    h = HealthState()
    h.note_round_stored(5, 0.1, 30)
    genesis, period = 1000, 30
    now = genesis + period * 9  # expected round 10, head 5
    snap = h.observe_chain(now, period, genesis)
    assert snap["expected_round"] == 10
    assert snap["lag_rounds"] == 5
    assert snap["missed_total"] == 4  # rounds 6..9 fully elapsed
    # same instant again: nothing new to count
    assert h.observe_chain(now, period, genesis)["missed_total"] == 4
    # chain catches up: misses stay counted, lag clears
    for r in range(6, 11):
        h.note_round_stored(r, 0.1, period)
    snap = h.observe_chain(now, period, genesis)
    assert snap["missed_total"] == 4 and snap["lag_rounds"] == 0


def test_health_unknown_head_never_counts_missed():
    """A head of 0 (fresh relay before its first successful tip fetch)
    must not turn the whole chain height into missed rounds — a
    transient fetch failure cannot permanently inflate a Counter."""
    h = HealthState()
    genesis, period = 1000, 30
    snap = h.observe_chain(genesis + period * 1000, period, genesis,
                           head_round=0)
    assert snap["missed_total"] == 0
    assert snap["lag_rounds"] > 0  # lag still reported
    # once a real head exists, counting starts from there — not from 0
    h.note_round_stored(995, 0.1, period)
    snap = h.observe_chain(genesis + period * 1000, period, genesis)
    assert snap["missed_total"] == snap["expected_round"] - 1 - 995


def test_health_backfill_excluded_from_slo():
    """Catch-up-stored rounds (lateness > 2 periods) advance the head
    but never enter the lateness histogram or the SLO window."""
    h = HealthState(window=8)
    period = 30
    lat0 = _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_round_lateness_seconds")
    for r in range(1, 6):
        h.note_round_stored(r, 3600.0, period)  # an hour stale: backfill
    assert h.snapshot()["head_round"] == 5
    assert h.snapshot()["slo_late_fraction"] == 0.0
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_round_lateness_seconds") == lat0
    h.note_round_stored(6, 0.2, period)  # live again
    assert h.snapshot()["slo_late_fraction"] == 0.0
    assert _sample_count(metrics.GROUP_REGISTRY,
                         "beacon_round_lateness_seconds") == lat0 + 1


def test_health_slo_window_and_sync_progress():
    h = HealthState(window=4)
    for r, late_by in enumerate((0.1, 20.0, 0.2, 21.0), start=1):
        h.note_round_stored(r, late_by, 30)  # late threshold: 15 s
    assert h.snapshot()["slo_late_fraction"] == 0.5
    h.note_sync_progress(done=100, elapsed_s=10.0, current=500,
                         target=1000)
    snap = h.snapshot()["sync"]
    assert snap["rounds_per_sec"] == 10.0
    assert snap["eta_seconds"] == 50.0
    assert metrics.SYNC_ROUNDS_PER_SEC._value.get() == 10.0
    h.note_sync_progress(0, 0.0, 0, 0, active=False)
    assert metrics.SYNC_ROUNDS_PER_SEC._value.get() == 0.0
    assert metrics.SYNC_ETA_SECONDS._value.get() == 0.0
    h.note_sync_progress(done=10, elapsed_s=1.0, current=50, target=0)
    assert metrics.SYNC_ETA_SECONDS._value.get() == -1.0  # unbounded


# ---------------------------------------------------------------------------
# fallback ledger + compile-time split
# ---------------------------------------------------------------------------

class _WedgedEngine:
    def wire_rlc_active(self, n):
        return False

    def verify_beacons(self, *a, **k):
        raise RuntimeError("device wedged (test)")


def test_fallback_ledger_bounds_and_dispatch(monkeypatch):
    batch.reset_fallback_ledger()
    for i in range(batch.FALLBACK_LEDGER_MAX + 40):
        batch._ledger_note(f"op{i}", "device", "x" * 1000)
    led = batch.fallback_ledger()
    assert len(led) == batch.FALLBACK_LEDGER_MAX
    assert led[-1]["op"] == f"op{batch.FALLBACK_LEDGER_MAX + 39}"
    assert all(len(e["reason"]) <= 300 for e in led)

    # a real device failure through the dispatcher lands an entry with
    # op/path/reason and still returns the host verdicts
    batch.reset_fallback_ledger()
    monkeypatch.delenv("DRAND_TPU_BATCH_VERIFY", raising=False)
    sk, pub = bls.keygen(seed=b"ledger-test")
    beacons = _make_chain(sk, 2)
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("auto", min_batch=1, engine=_WedgedEngine())
    try:
        out = batch.verify_beacons(pub, beacons)
        assert out.all() and len(out) == 2
    finally:
        batch._MODE, batch._MIN_BATCH, batch._ENGINE = old
    led = batch.fallback_ledger()
    assert len(led) == 1
    assert led[0]["op"] == "verify_beacons"
    assert led[0]["path"] == "device"
    assert "device wedged" in led[0]["reason"]


def test_compile_seconds_first_call_split():
    op = "zz_obs_test_op"
    key = (op, "device", "8")
    batch._WARM_SHAPES.discard(key)
    c0 = _sample_count(metrics.REGISTRY, "engine_compile_seconds", op=op)
    o0 = _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op,
                       path="device", batch="8")
    with batch._timed(op, "device", 8):
        pass
    assert _sample_count(metrics.REGISTRY, "engine_compile_seconds",
                         op=op) == c0 + 1
    assert _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op,
                         path="device", batch="8") == o0
    with batch._timed(op, "device", 8):
        pass  # warm now: steady-state series moves
    assert _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op,
                         path="device", batch="8") == o0 + 1
    # host paths never divert (no compile to split out)
    h0 = _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op,
                       path="host", batch="8")
    with batch._timed(op, "host", 8):
        pass
    assert _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op,
                         path="host", batch="8") == h0 + 1
    assert _sample_count(metrics.REGISTRY, "engine_compile_seconds",
                         op=op) == c0 + 1
    # a FAILED first dispatch stays in <path>_error and does not warm
    op2 = "zz_obs_test_op_fail"
    batch._WARM_SHAPES.discard((op2, "device", "8"))
    with pytest.raises(RuntimeError):
        with batch._timed(op2, "device", 8):
            raise RuntimeError("boom")
    assert _sample_count(metrics.REGISTRY, "engine_op_seconds", op=op2,
                         path="device_error", batch="8") == 1
    assert (op2, "device", "8") not in batch._WARM_SHAPES


# ---------------------------------------------------------------------------
# cross-node timeline merge (util trace --merge core)
# ---------------------------------------------------------------------------

def test_merge_two_tracers_interleaves_shared_round():
    """Two nodes' rings, same deterministic trace id: the merge yields
    ONE timeline with both nodes' spans ordered by wall-clock start."""
    seed = b"merge-chain"
    ta, tb = trace.Tracer(), trace.Tracer()
    with ta.activate(round_no=9, chain=seed):
        with ta.span("partial", node="a"):
            pass
    with tb.activate(round_no=9, chain=seed):
        with tb.span("partial_verify", node="b"):
            pass
    with ta.activate(round_no=9, chain=seed):
        with ta.span("store", node="a"):
            pass
    # an unshared round on node b only
    with tb.activate(round_no=10, chain=seed):
        with tb.span("partial", node="b"):
            pass
    merged = trace.merge_round_timelines([
        ("http://a:1", {"rounds": ta.rounds(8)}),
        ("http://b:1", {"rounds": tb.rounds(8)}),
    ])
    by_round = {m["round"]: m for m in merged}
    shared = by_round[9]
    assert shared["trace_id"] == trace.round_trace_id(9, seed)
    assert shared["nodes"] == ["http://a:1", "http://b:1"]
    assert [s["name"] for s in shared["spans"]] == \
        ["partial", "partial_verify", "store"]
    assert [s["node"] for s in shared["spans"]] == \
        ["http://a:1", "http://b:1", "http://a:1"]
    starts = [s["start"] for s in shared["spans"]]
    assert starts == sorted(starts)
    assert by_round[10]["nodes"] == ["http://b:1"]
    assert merged[0]["round"] == 10  # most recent first


# ---------------------------------------------------------------------------
# /debug/trace/rounds hardening + /debug/engine + Tracer.reset race
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_trace_rounds_n_validation_and_engine_endpoint():
    app = web.Application()
    add_trace_routes(app)
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = site._server.sockets[0].getsockname()[1]
    try:
        # NB: a literal '+' in a query string decodes to a space, so the
        # explicit-sign probes are percent-encoded
        for q, want in (("zzz", 400), ("1.5", 400), ("1e3", 400),
                        ("0x10", 400), ("", 400), ("%2B-5", 400),
                        ("-5", 200), ("0", 200), ("999999999", 200),
                        ("%2B7", 200), ("8", 200)):
            status, body = await _get(port, f"/debug/trace/rounds?n={q}")
            assert status == want, f"n={q!r} -> {status}, want {want}"
            if want == 200:
                assert "rounds" in body
        status, body = await _get(port, "/debug/engine")
        assert status == 200
        assert body["mode"] in ("auto", "device", "host")
        assert isinstance(body["engine_created"], bool)
        assert isinstance(body["fallback_ledger"], list)
        assert set(body["h2c_cache"]) >= {"hits", "misses", "size"}
        assert isinstance(body["warm_shapes"], list)
    finally:
        await runner.cleanup()


def test_tracer_reset_safe_against_concurrent_record():
    t = trace.Tracer(max_rounds=8, max_spans=64)
    stop = threading.Event()
    errs = []

    def hammer(i):
        try:
            while not stop.is_set():
                with t.activate(round_no=i, chain=b"race"):
                    with t.span("s", i=i):
                        pass
        except Exception as e:  # noqa: BLE001 — any raise fails the test
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(i,))
               for i in range(4)]
    for th in threads:
        th.start()
    try:
        for _ in range(300):
            t.reset()
            t.rounds(8)
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=10)
    assert not errs
    assert all(not th.is_alive() for th in threads)
    for rec in t.rounds(8):  # ring left structurally consistent
        assert set(rec) == {"trace_id", "round", "dropped", "spans"}


# ---------------------------------------------------------------------------
# engine introspection dict (no device engine needed: shape only)
# ---------------------------------------------------------------------------

def test_engine_introspect_json_shape():
    """introspect() must be JSON-ready (string keys for tuple-keyed KAT
    caches) — exercised against a real BatchedEngine only when some
    other suite in this process already created one; otherwise a stub
    engine with populated caches checks the key conversion."""
    import json as _json

    eng = batch._ENGINE
    if eng is None or not hasattr(eng, "introspect"):
        from drand_tpu.ops.engine import BatchedEngine

        eng = BatchedEngine.__new__(BatchedEngine)  # no jit/compile
        eng.buckets = (4, 128)
        eng.mesh = None
        eng.rlc_min = 8
        eng.rlc_lane_buckets = (8, 32)
        eng.wire_prep = None
        eng.gls4 = True
        eng._bucket_ok = {4: True}
        eng._wire_ok = {128: False}
        eng._rlc_ok = {("g2g2", 8): True}
        eng._wire_rlc_ok = {32: True}
        eng._wire_rlc_sharded_ok = {}
        eng._tl_ok = {8: True}
        eng._eval_ok = {(2, 32): True}
        eng._poly_eval_ok = {}
        eng._agg_ok = {(4, 8, 255): False}
    data = eng.introspect()
    _json.dumps(data)  # every key/value serializes
    assert data["backend"]
    kat = data["kat"]
    assert set(kat) == {"verify", "wire", "rlc", "wire_rlc",
                        "wire_rlc_sharded", "timelock", "eval",
                        "poly_eval", "agg"}
    for family in kat.values():
        for k, v in family.items():
            assert isinstance(k, str) and isinstance(v, bool)
