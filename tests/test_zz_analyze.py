"""Static-analysis suite (ISSUE 7): fixture snippets per pass, the
baseline round-trip, the real-tree gate, and the event-loop-offload
regression the loopblock pass exists to prevent.

Late-alphabet filename on purpose: tier-1 on the 1-core box runs in
chunks (tools/tier1_chunks.sh) and newer suites sort last so the capped
single invocation keeps its early-dot throughput. Everything here is
host-only — pure AST plus one monkeypatched aiohttp harness; no device
graphs, no fresh XLA compiles, no backend init.
"""

import asyncio
import textwrap
import threading
import time
import types

import numpy as np
import pytest

from tools.analyze import asyncsanity, jaxhazard, loopblock, secretflow
from tools.analyze.core import Project
from tools.analyze.run import REPO, load_baseline, run_analysis

# ---------------------------------------------------------------------------
# fixture helpers
# ---------------------------------------------------------------------------


def _tree(tmp_path, files: dict) -> str:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _project(tmp_path, files: dict) -> Project:
    return Project(_tree(tmp_path, files))


# ---------------------------------------------------------------------------
# loopblock
# ---------------------------------------------------------------------------


def test_loopblock_direct_and_transitive(tmp_path):
    """An async def reaching time.sleep through two sync hops is
    flagged with the call path; the to_thread twin is clean."""
    proj = _project(tmp_path, {
        "app/svc.py": """
            import asyncio
            import time

            def inner():
                time.sleep(1.0)

            def outer():
                inner()

            async def bad_handler():
                outer()

            async def good_handler():
                await asyncio.to_thread(outer)
        """,
    })
    findings = loopblock.run(proj)
    symbols = {f.symbol for f in findings}
    assert "app.svc.bad_handler" in symbols
    assert "app.svc.good_handler" not in symbols
    bad = next(f for f in findings if f.symbol == "app.svc.bad_handler")
    assert bad.severity == "medium"
    assert "time.sleep" in bad.message and "outer" in bad.message


def test_loopblock_retry_sleep_rule(tmp_path):
    """ISSUE 12 (scope widened by ISSUE 14): a raw asyncio.sleep inside
    a retry/backoff loop (a loop that both handles exceptions and backs
    off) in net/, chain/, timelock/, http_server/ or relay/ is a medium
    finding — retries there must ride the injectable-clock policy.
    Cooperative sleep(0) yields, clock-policy sleeps, loops without
    exception handling, and the same shape OUTSIDE the scoped packages
    all stay clean."""
    proj = _project(tmp_path, {
        "drand_tpu/net/dialer.py": """
            import asyncio

            async def bad_dial(peer):
                while True:
                    try:
                        return await peer.call()
                    except ConnectionError:
                        await asyncio.sleep(0.5)

            async def yield_only(stream):
                for _ in range(4):
                    try:
                        pass
                    except ValueError:
                        pass
                    await asyncio.sleep(0)

            async def policy_backoff(peer, clock):
                while True:
                    try:
                        return await peer.call()
                    except ConnectionError:
                        await clock.sleep(0.5)

            async def plain_poll(peer):
                while True:
                    await asyncio.sleep(0.5)
        """,
        # http_server/ and relay/ are IN scope since the relay watch
        # loop moved onto the policy (ISSUE 14) — the exact shape the
        # old PublicServer._watch_loop restart path had is now flagged
        "drand_tpu/http_server/watchish.py": """
            import asyncio

            async def bad_watch_loop(client):
                while True:
                    try:
                        async for r in client.watch():
                            pass
                    except Exception:
                        await asyncio.sleep(1.0)
        """,
        "drand_tpu/relay/pump.py": """
            import asyncio

            async def bad_forward(peer):
                while True:
                    try:
                        return await peer.call()
                    except ConnectionError:
                        await asyncio.sleep(0.5)
        """,
        # the consuming client stack stays OUT of scope: its poll
        # cadence is wall-clock by design (client/http.py watch)
        "drand_tpu/client/poller.py": """
            import asyncio

            async def out_of_scope(peer):
                while True:
                    try:
                        return await peer.call()
                    except ConnectionError:
                        await asyncio.sleep(0.5)
        """,
    })
    findings = [f for f in loopblock.run(proj)
                if f.rule == "retry-sleep"]
    assert {f.symbol for f in findings} == {
        "drand_tpu.net.dialer.bad_dial",
        "drand_tpu.http_server.watchish.bad_watch_loop",
        "drand_tpu.relay.pump.bad_forward",
    }
    f = next(f for f in findings
             if f.symbol == "drand_tpu.net.dialer.bad_dial")
    assert f.severity == "medium"
    assert "injectable-clock" in f.message
    assert f.key.endswith(":retry-sleep")


def test_real_tree_no_retry_sleep_findings():
    """The live tree is clean under the new rule with ZERO baseline
    entries — every retry loop in net/, chain/ and timelock/ already
    goes through drand_tpu.utils.retry."""
    proj = Project(REPO, packages=("drand_tpu",))
    assert [f for f in loopblock.run(proj)
            if f.rule == "retry-sleep"] == []


def test_loopblock_pairing_class_is_high(tmp_path):
    """Project-shaped fixture: engine dispatch reachable from an async
    def is high severity — the exact seed bug (sync.py:146)."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def verify_beacons(pub, beacons):
                return [True] * len(beacons)
        """,
        "app/syncer.py": """
            import asyncio
            from drand_tpu.crypto import batch

            async def follow(pub, chunk):
                return batch.verify_beacons(pub, chunk)

            async def follow_offloaded(pub, chunk):
                return await asyncio.to_thread(
                    batch.verify_beacons, pub, chunk)
        """,
    })
    findings = loopblock.run(proj)
    by_symbol = {f.symbol: f for f in findings}
    assert by_symbol["app.syncer.follow"].severity == "high"
    assert "pairing-class" in by_symbol["app.syncer.follow"].message
    # the executor hand-off passes the function as an ARGUMENT — no call
    # edge, no finding: this is what "fixed" means mechanically
    assert "app.syncer.follow_offloaded" not in by_symbol


def test_loopblock_lambda_wrapper_is_neutral(tmp_path):
    """A lambda body runs when the lambda is CALLED, not where it is
    written: `await asyncio.to_thread(lambda: batch.verify(...))` is a
    correct hand-off and must not create a call edge from the
    enclosing async def."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def verify_beacons(pub, beacons):
                return [True] * len(beacons)
        """,
        "app/syncer.py": """
            import asyncio
            from drand_tpu.crypto import batch

            async def follow_lambda(pub, chunk):
                return await asyncio.to_thread(
                    lambda: batch.verify_beacons(pub, chunk))
        """,
    })
    assert loopblock.run(proj) == []


def test_loopblock_unresolved_attr_fallback(tmp_path):
    """obj.aggregate_round(...) on an unresolvable receiver still taints
    via the curated attribute list."""
    proj = _project(tmp_path, {
        "app/agg.py": """
            async def aggregate(engine, parts):
                return engine.aggregate_round(parts)
        """,
    })
    findings = loopblock.run(proj)
    assert [f.symbol for f in findings] == ["app.agg.aggregate"]
    assert findings[0].severity == "high"


# ---------------------------------------------------------------------------
# secretflow
# ---------------------------------------------------------------------------


def test_secretflow_sinks(tmp_path):
    proj = _project(tmp_path, {
        "app/keys.py": """
            def setup(logger, metrics_counter, tracer, pri_share):
                secret = derive(pri_share)
                logger.info("dkg", share=pri_share)
                metrics_counter.labels(key=str(secret)).inc()
                tracer.span("deal", secret=secret)
                raise ValueError(f"bad share: {pri_share}")
        """,
    })
    findings = secretflow.run(proj)
    rules = sorted(f.rule for f in findings)
    assert rules == ["secret-in-exception", "secret-in-log",
                     "secret-in-metric-label", "secret-in-trace-attr"]
    assert all(f.severity == "high" for f in findings)


def test_secretflow_laundering_and_module_alias(tmp_path):
    """Non-converter call results do not taint (an RPC fed a secret
    returns a status, not the secret), and the stdlib `secrets` module
    alias never taints."""
    proj = _project(tmp_path, {
        "app/clean.py": """
            import secrets

            async def share(ctl, logger, secret):
                out = await ctl.init_dkg(secret)
                print(out)
                logger.info("nonce", n=secrets.token_hex(8))
                logger.info("size", n=len(secret))
        """,
        "app/leak.py": """
            def show(secret):
                print(str(secret))
        """,
    })
    findings = secretflow.run(proj)
    assert [f.path for f in findings] == ["app/leak.py"]
    assert findings[0].rule == "secret-in-print"


def test_secretflow_catches_secret_logging_chaos_scenario(tmp_path):
    """ISSUE 11 satellite: a chaos fault-schedule harness that logs a
    node's secret share while reporting a fault (the exact hygiene
    violation the chaos suite asserts never happens at runtime) is a
    HIGH secretflow finding — the static gate backs the runtime check,
    so a scenario author cannot even merge the leak."""
    proj = _project(tmp_path, {
        "testing/chaos_ext.py": """
            def report_byzantine(logger, metrics_counter, node, share):
                pri_share = share.pri_share
                logger.warn("chaos", "byzantine_detected",
                            node=node, share=pri_share)
                metrics_counter.labels(peer=str(pri_share)).inc()
        """,
    })
    findings = secretflow.run(proj)
    rules = sorted(f.rule for f in findings)
    assert rules == ["secret-in-log", "secret-in-metric-label"]
    assert all(f.severity == "high" for f in findings)
    assert all(f.path == "testing/chaos_ext.py" for f in findings)


def test_secretflow_bundle_writer_sink(tmp_path):
    """ISSUE 15 satellite: the incident/forensic bundle writers are a
    registered sink class — a pri_share routed into a bundle lands on
    disk and travels to whoever reads the post-mortem, exfiltration
    exactly like logging it. Known-bad: secret args into the writer
    calls (bare and method forms) are HIGH. Known-good: telemetry
    fields through the same writers stay clean."""
    proj = _project(tmp_path, {
        "obs/leaky.py": """
            def on_trigger(mgr, share, rule):
                pri_share = share.pri_share
                mgr.capture_bundle(reason=str(pri_share))
                freeze_bundle(rule, evidence=pri_share)
        """,
        "obs/clean_bundle.py": """
            def on_trigger(mgr, flight, health, rule):
                bundle = freeze_bundle(rule, flight=flight.rounds(8),
                                       health=health.snapshot())
                mgr.write_bundle(rule.name, bundle)
        """,
    })
    findings = secretflow.run(proj)
    assert [f.path for f in findings] == ["obs/leaky.py"] * 2
    assert all(f.rule == "secret-in-bundle" for f in findings)
    assert all(f.severity == "high" for f in findings)


# ---------------------------------------------------------------------------
# jaxhazard
# ---------------------------------------------------------------------------


def test_jaxhazard_tracer_branch_and_dynamic_shape(tmp_path):
    proj = _project(tmp_path, {
        "ops/kernels.py": """
            from functools import partial

            import jax
            import jax.numpy as jnp

            @jax.jit
            def bad_branch(x):
                y = jnp.abs(x)
                if y > 0:
                    return x
                return -x

            @jax.jit
            def bad_shape(n):
                return jnp.zeros(n)

            @partial(jax.jit, static_argnames=("n",))
            def good_shape(n):
                return jnp.zeros(n)

            @jax.jit
            def good_lax(x):
                return jax.lax.select(x > 0, x, -x)

            def bad_per_call(f, x):
                return jax.jit(f)(x)
        """,
    })
    findings = jaxhazard.run(proj, float_dtype_dirs=())
    rules = {(f.rule, f.symbol.rsplit(".", 1)[-1]) for f in findings}
    assert ("tracer-branch", "bad_branch") in rules
    assert ("dynamic-shape", "bad_shape") in rules
    assert ("jit-per-call", "bad_per_call") in rules
    assert not any(s in ("good_shape", "good_lax")
                   for _, s in rules)


def test_jaxhazard_posonly_and_kwonly_params(tmp_path):
    """static_argnums indexes the full positional list (posonlyargs
    first), and keyword-only params trace like any other argument —
    misreading either direction flips a real hazard into silence or a
    static param into noise."""
    proj = _project(tmp_path, {
        "ops/kernels.py": """
            from functools import partial

            import jax
            import jax.numpy as jnp

            @partial(jax.jit, static_argnums=(0,))
            def posonly(n, /, x):
                for _ in range(n):     # n IS static — no finding
                    x = x + 1
                if x > 0:              # x is traced — finding
                    return x
                return -x

            @jax.jit
            def kwonly(x, *, flag=None):
                if flag:               # kw-only params trace too
                    return x
                return -x
        """,
    })
    findings = jaxhazard.run(proj, float_dtype_dirs=())
    rules = {(f.rule, f.symbol.rsplit(".", 1)[-1]) for f in findings}
    assert ("tracer-branch", "posonly") in rules
    assert ("tracer-branch", "kwonly") in rules
    assert ("dynamic-shape", "posonly") not in rules


def test_jaxhazard_float_dtype_in_limb_math(tmp_path):
    proj = _project(tmp_path, {
        "ops/limbstuff.py": """
            import jax.numpy as jnp

            def mul(a):
                return a.astype(jnp.float32)
        """,
        "ops/clean.py": """
            import jax.numpy as jnp

            def double(v):
                return jnp.left_shift(v, 1)
        """,
        # "ops/" must match whole path components — a loops/ package is
        # NOT limb math and may use floats freely
        "loops/sched.py": """
            import jax.numpy as jnp

            def weights(n):
                return jnp.ones(n, dtype=jnp.float32)
        """,
    })
    findings = jaxhazard.run(proj)
    assert [f.rule for f in findings] == ["float-dtype"]
    assert findings[0].severity == "high"
    assert "limbstuff" in findings[0].path


# ---------------------------------------------------------------------------
# asyncsanity
# ---------------------------------------------------------------------------


def test_asyncsanity_unawaited_and_fire_and_forget(tmp_path):
    proj = _project(tmp_path, {
        "drand_tpu/utils/aio.py": """
            import asyncio

            def spawn(coro):
                task = asyncio.ensure_future(coro)
                _TASKS.add(task)
                task.add_done_callback(_TASKS.discard)
                return task

            _TASKS = set()
        """,
        "app/tasks.py": """
            import asyncio
            from drand_tpu.utils.aio import spawn

            async def work():
                pass

            def bad_unawaited():
                work()

            def bad_weak_ref():
                asyncio.ensure_future(work())
                asyncio.create_task(work())

            def good_spawn():
                spawn(work())

            def good_kept():
                t = asyncio.create_task(work())
                return t
        """,
    })
    findings = asyncsanity.run(proj)
    by_symbol = {}
    for f in findings:
        by_symbol.setdefault(f.symbol.rsplit(".", 1)[-1], []).append(f.rule)
    assert by_symbol == {
        "bad_unawaited": ["unawaited-coroutine"],
        "bad_weak_ref": ["task-without-ref", "task-without-ref"],
    }


@pytest.mark.asyncio
async def test_spawn_holds_strong_reference():
    """utils.aio.spawn keeps the task alive with no caller-side ref."""
    import gc

    from drand_tpu.utils import aio

    done = asyncio.Event()

    async def work():
        await asyncio.sleep(0.05)
        done.set()

    aio.spawn(work())  # deliberately discarded
    assert aio.pending_tasks() >= 1
    gc.collect()
    await asyncio.wait_for(done.wait(), 2.0)
    await asyncio.sleep(0)
    assert aio.pending_tasks() == 0


# ---------------------------------------------------------------------------
# baseline round-trip
# ---------------------------------------------------------------------------


def _one_finding_tree(tmp_path) -> str:
    return _tree(tmp_path, {
        "app/svc.py": """
            import time

            async def handler():
                time.sleep(1.0)
        """,
    })


def test_baseline_roundtrip(tmp_path):
    root = _one_finding_tree(tmp_path)
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=tmp_path / "missing.json")
    assert [f.symbol for f in report["findings"]] == ["app.svc.handler"]
    key = report["findings"][0].key

    # suppressed finding stays suppressed...
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": [{"key": "%s", "reason": '
                  '"fixture: documented test suppression"}]}' % key)
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=bl)
    assert report["findings"] == []
    assert [f.key for f in report["suppressed"]] == [key]

    # ...a NEW finding still fails
    (tmp_path / "app" / "new.py").write_text(textwrap.dedent("""
        import time

        async def fresh():
            time.sleep(2.0)
    """))
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=bl)
    assert [f.symbol for f in report["findings"]] == ["app.new.fresh"]


def test_baseline_entry_is_scoped_to_the_reviewed_leaf(tmp_path):
    """A loopblock suppression names the blocking leaf it reviewed: a
    DIFFERENT (stronger) blocking call added to the same function later
    must surface as a new, unsuppressed finding — the zero-high gate
    would otherwise be silently defeated for every baselined symbol."""
    root = _one_finding_tree(tmp_path)
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=tmp_path / "missing.json")
    key = report["findings"][0].key
    assert key.endswith("time.sleep (time.sleep)")  # leaf in the key
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": [{"key": "%s", "reason": '
                  '"fixture: reviewed sleep stays inline"}]}' % key)

    # same function grows a pairing-class call: new leaf, new key
    (tmp_path / "app" / "svc.py").write_text(textwrap.dedent("""
        import time

        from drand_tpu.crypto import batch

        async def handler():
            time.sleep(1.0)
            batch.verify_beacons([], [])
    """))
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=bl)
    highs = [f for f in report["findings"] if f.severity == "high"]
    assert len(highs) == 1 and "verify_beacons" in highs[0].key
    # the reviewed-sleep entry now matches nothing (the high leaf wins
    # the per-function finding) and is flagged for cleanup
    assert any(f.rule == "stale-entry" for f in report["findings"])


def test_baseline_requires_reason_and_flags_stale(tmp_path):
    root = _one_finding_tree(tmp_path)
    bl = tmp_path / "baseline.json"
    bl.write_text('{"entries": ['
                  '{"key": "loopblock:async-blocking-medium:app/svc.py:'
                  'app.svc.handler", "reason": ""},'
                  '{"key": "loopblock:gone:app/old.py:app.old.f", '
                  '"reason": "fixture: the code this covered was removed"}'
                  ']}')
    report = run_analysis(root=root, passes=("loopblock",),
                          baseline_path=bl)
    rules = {f.rule for f in report["findings"]}
    # empty reason -> high finding + the suppression does NOT apply;
    # unmatched entry -> stale-entry
    assert "missing-reason" in rules
    assert "stale-entry" in rules
    assert any(f.symbol == "app.svc.handler" for f in report["findings"])


# ---------------------------------------------------------------------------
# the real tree
# ---------------------------------------------------------------------------


def test_real_tree_zero_unsuppressed_high(tmp_path):
    """The PR gate: the repo analyzes clean at --fail-on=high, every
    baseline entry carries a written reason, and the run is host-only
    fast (no backend init — pure AST). On failure the findings are
    ALSO written as SARIF next to the test log (ISSUE 13 satellite:
    auditable CI annotations for exactly what failed the gate)."""
    from tools.analyze.run import write_sarif

    t0 = time.perf_counter()
    report = run_analysis()
    elapsed = time.perf_counter() - t0
    highs = [f for f in report["findings"] if f.severity == "high"]
    if highs:
        sarif_path = tmp_path / "analyze-failure.sarif"
        write_sarif(report, sarif_path)
        print(f"\nanalyze gate FAILED — SARIF written to {sarif_path}")
    assert highs == [], "\n".join(f.render() for f in highs)
    baseline, problems = load_baseline(
        REPO / "tools" / "analyze" / "baseline.json")
    assert problems == []
    assert all(len(r.strip()) >= 10 for r in baseline.values())
    assert elapsed < 60.0


def test_real_tree_no_pairing_class_async_paths():
    """The acceptance criterion, stated directly: NO pairing-class call
    (pairings, Miller loops, MSM, engine dispatch, tbls) is reachable
    from any async def in drand_tpu without an executor hand-off —
    except paths carrying a reviewed baseline entry (currently exactly
    one: the DKG's phase-window deal admission)."""
    proj = Project(REPO, packages=("drand_tpu",))
    baseline, problems = load_baseline(
        REPO / "tools" / "analyze" / "baseline.json")
    assert problems == []
    highs = [f for f in loopblock.run(proj)
             if f.severity == "high" and f.key not in baseline]
    assert highs == [], "\n".join(f.render() for f in highs)
    # the suppression list itself stays tight: reviewed entries only —
    # one loopblock (DKG deal admission) and one lockheld (engine
    # singleton init, see test_zz_concurrency)
    assert len([k for k in baseline if k.startswith("loopblock:")]) <= 1
    assert len(baseline) <= 2


def test_metrics_pass_folds_into_runner():
    """check_metrics rides along as the fifth pass (one tier-1 entry
    point) and is clean on the repo."""
    report = run_analysis(passes=("metrics",))
    assert report["findings"] == []


# ---------------------------------------------------------------------------
# the offload regression: /healthz answers while a span verifies
# ---------------------------------------------------------------------------


class _StubClient:
    """Minimal Client for PublicServer: serves info, never a beacon."""

    async def info(self):
        return types.SimpleNamespace(period=30, genesis_time=0)

    async def get(self, round_no: int = 0):
        from drand_tpu.client.interface import ClientError

        raise ClientError("no beacon in stub")

    async def watch(self):
        await asyncio.Event().wait()
        yield None  # pragma: no cover

    def round_at(self, t):
        return 0

    async def close(self):
        pass


@pytest.mark.asyncio
async def test_healthz_answers_while_large_span_verifies(monkeypatch):
    """The two highest-severity loopblock findings, fixed: Syncer span
    verification runs via asyncio.to_thread, so a multi-second
    verify_beacons call no longer freezes the event loop — /healthz
    keeps answering mid-verification."""
    import aiohttp

    from drand_tpu.chain.beacon import Beacon
    from drand_tpu.chain.engine import sync as sync_mod
    from drand_tpu.chain.store import CallbackStore, MemStore
    from drand_tpu.crypto import batch
    from drand_tpu.http_server.server import PublicServer
    from drand_tpu.obs.state import reset_observability
    from drand_tpu.utils.logging import default_logger

    reset_observability()
    in_verify = threading.Event()

    def slow_verify(pub, chunk, dst=None):
        # stands in for a large catch-up span's pairing work: BLOCKS its
        # thread for longer than the healthz deadline below
        in_verify.set()
        time.sleep(1.2)
        return np.ones(len(chunk), dtype=bool)

    monkeypatch.setattr(batch, "verify_beacons", slow_verify)

    store = CallbackStore(MemStore())
    store.put(Beacon(round=0, previous_sig=b"", signature=b"genesis"))
    info = types.SimpleNamespace(public_key=None, genesis_seed=b"t")

    beacons = [Beacon(round=r, previous_sig=bytes(32), signature=bytes(96))
               for r in range(1, 65)]

    class _StubTransport:
        def sync_chain(self, peer, req):
            async def gen():
                for b in beacons:
                    yield b
            return gen()

    syncer = sync_mod.Syncer(default_logger("test.sync"), store, info,
                             _StubTransport())

    server = PublicServer(_StubClient())
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    try:
        follow = asyncio.ensure_future(syncer.follow(64, ["peer"]))
        # wait until the (threaded) verification is actually blocking
        for _ in range(200):
            if in_verify.is_set():
                break
            await asyncio.sleep(0.01)
        assert in_verify.is_set()

        # the loop must answer well inside the 1.2 s verify window; a
        # regression to inline verification deadlocks this request
        t0 = time.perf_counter()
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}/healthz",
                             timeout=aiohttp.ClientTimeout(total=1.0)) as r:
                assert r.status in (200, 503)
                await r.json()
        assert time.perf_counter() - t0 < 1.0

        assert await asyncio.wait_for(follow, 10.0) is True
        assert store.last().round == 64
    finally:
        await server.stop()
        reset_observability()
