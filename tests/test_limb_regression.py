"""Regression witnesses for the limb-arithmetic reduction soundness bug.

Round-3 find: ``reduce_light`` (both ops/limb.py and ops/bl.py) ran only
TWO wrap passes; with lazy-carry inputs from ``sub`` the value after pass
2 can still exceed 2^384, and ``_wrap`` truncates the live carry limb —
silently subtracting 2^384 (≡ −R mod p) from the result. Hit probability
is ~2^-12 per sub of non-canonical operands, i.e. roughly 1% of pairings;
the concrete witness below came from a FAILING valid BLS verification
(message b"pack-126" under sk 0x77: the Miller loop's sparse multiply at
iteration 41 produced c1 short by exactly 1).

The fix is a third wrap pass with a proved value bound (see
limb.reduce_light docstring).
"""

import numpy as np

from drand_tpu.crypto.fields import Fp2, P
from drand_tpu.ops import bl, limb

# device-representation (lazy-carry, Montgomery-domain) Fp2 operands
# captured from the failing pairing — limbs of (c0, c1), 12-bit radix
A = [[3461, 2515, 2759, 2235, 118, 2074, 2474, 3336, 979, 3400, 613, 1831,
      1542, 50, 480, 789, 1219, 1623, 3427, 3724, 5, 1514, 3687, 1802,
      2551, 3429, 1921, 2576, 3515, 195, 14, 1720],
     [1365, 2066, 3417, 3684, 3327, 3236, 2642, 2046, 230, 2880, 956, 1158,
      801, 3865, 147, 99, 1343, 1271, 4040, 349, 1166, 776, 594, 3550,
      1339, 2897, 3043, 3619, 3879, 1805, 328, 3142]]
B = [[860, 4066, 1373, 3047, 3051, 2449, 3963, 3164, 3415, 3149, 4064, 126,
      3653, 3055, 1142, 3530, 565, 1965, 2348, 2696, 2099, 2809, 1985,
      3006, 3344, 598, 340, 934, 303, 4038, 1453, 961],
     [1208, 3656, 2099, 1926, 3540, 3081, 2570, 2415, 2752, 2232, 2685,
      2872, 1780, 2714, 295, 1034, 314, 273, 2609, 3411, 2539, 1690, 543,
      1636, 3530, 1661, 3809, 2440, 1042, 3741, 2803, 699]]

R_INV = pow(1 << 384, -1, P)


def _val(limbs) -> int:
    return sum(int(v) << (12 * i) for i, v in enumerate(limbs))


def _fp2_of(rows) -> Fp2:
    # device arrays are Montgomery-domain: value = limbs / R mod p
    return Fp2(_val(rows[0]) * R_INV % P, _val(rows[1]) * R_INV % P)


def test_f2_mul_witness_bl():
    a_np = np.asarray(A, np.int32)[:, :, None]
    b_np = np.asarray(B, np.int32)[:, :, None]
    out = np.asarray(bl.f2_mul(a_np, b_np))
    got = Fp2(limb.fp_from_device(out[0, :, 0]) % P,
              limb.fp_from_device(out[1, :, 0]) % P)
    exp = _fp2_of(A) * _fp2_of(B)
    assert got == exp


def test_f2_mul_witness_limb_path():
    from drand_tpu.ops import tower

    # limb-last layout: (..., 2, 32)
    a_np = np.asarray(A, np.int32)
    b_np = np.asarray(B, np.int32)
    out = np.asarray(tower.f2_mul(a_np, b_np))
    got = Fp2(limb.fp_from_device(out[0]) % P,
              limb.fp_from_device(out[1]) % P)
    exp = _fp2_of(A) * _fp2_of(B)
    assert got == exp


def test_sub_then_wrap_carry_edge():
    """The distilled core: sub() whose reduce_light needs the third wrap
    pass. v2 - (v0 + v1) with the witness products."""
    a_np = np.asarray(A, np.int32)[:, :, None]
    b_np = np.asarray(B, np.int32)[:, :, None]
    v0 = np.asarray(bl.mont_mul(a_np[0], b_np[0]))
    v1 = np.asarray(bl.mont_mul(a_np[1], b_np[1]))
    sa = np.asarray(bl.add(a_np[0], a_np[1]))
    sb = np.asarray(bl.add(b_np[0], b_np[1]))
    v2 = np.asarray(bl.mont_mul(sa, sb))
    c1 = np.asarray(bl.sub(v2, bl.add(v0, v1)))
    got = limb.fp_from_device(c1[:, 0]) % P
    a2, b2 = _fp2_of(A), _fp2_of(B)
    exp = (a2 * b2).c1
    assert got == exp


def test_randomized_chain_against_host():
    """Chained f2 ops on random values, compared against the host field —
    broad fuzz over the non-canonical representation space."""
    import random

    rnd = random.Random(0xD1CE)
    for trial in range(20):
        av = Fp2(rnd.randrange(P), rnd.randrange(P))
        bv = Fp2(rnd.randrange(P), rnd.randrange(P))
        cv = Fp2(rnd.randrange(P), rnd.randrange(P))
        a_np = np.stack([bl.pack_fp([av.c0]), bl.pack_fp([av.c1])])
        b_np = np.stack([bl.pack_fp([bv.c0]), bl.pack_fp([bv.c1])])
        c_np = np.stack([bl.pack_fp([cv.c0]), bl.pack_fp([cv.c1])])
        # (a*b + c)^2 - a*c, all in non-canonical chained representation
        t = bl.f2_add(bl.f2_mul(a_np, b_np), c_np)
        t = bl.f2_sub(bl.f2_sqr(t), bl.f2_mul(a_np, c_np))
        out = np.asarray(t)
        got = Fp2(limb.fp_from_device(out[0, :, 0]) % P,
                  limb.fp_from_device(out[1, :, 0]) % P)
        exp = (av * bv + cv).square() - av * cv
        assert got == exp, f"trial {trial}"
