"""End-to-end: real OS processes, real TCP gRPC, real clock, driven only
through the CLI — the reference's demo orchestrator scenario
(demo/lib/orchestrator.go:61: spawn daemons, run DKG, check beacons via
HTTP, kill + restart a node, verify catchup).
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PERIOD = 2
SECRET = "e2e-cli-secret-0123456789abcdef0"


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def cli_env():
    env = dict(os.environ)
    # subprocesses run the pure-host protocol path: no axon sitecustomize,
    # no jax import, fast startup
    env["PYTHONPATH"] = REPO
    env.pop("JAX_PLATFORMS", None)
    return env


def run_cli(args, timeout=180):
    return subprocess.run(
        [sys.executable, "-m", "drand_tpu.cli", *args],
        capture_output=True, text=True, timeout=timeout, env=cli_env(),
        cwd=REPO)


class Node:
    def __init__(self, i, tmp_path):
        self.folder = str(tmp_path / f"node{i}")
        self.rpc_port = free_port()
        self.ctl_port = free_port()
        self.http_port = free_port()
        self.addr = f"127.0.0.1:{self.rpc_port}"
        self.proc = None

    def generate_keypair(self):
        out = run_cli(["generate-keypair", "--folder", self.folder, self.addr])
        assert out.returncode == 0, out.stderr

    def start(self):
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", "start",
             "--folder", self.folder, "--control", str(self.ctl_port),
             "--public-listen", f"127.0.0.1:{self.http_port}",
             "--dkg-timeout", "5"],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=cli_env(), cwd=REPO)
        deadline = time.time() + 30
        while time.time() < deadline:
            ping = run_cli(["util", "ping", "--control", str(self.ctl_port)],
                           timeout=10)
            if ping.returncode == 0 and "pong" in ping.stdout:
                return
            time.sleep(0.3)
        raise TimeoutError(f"daemon {self.addr} did not come up:\n"
                           f"{self.proc.stdout.read() if self.proc.stdout else ''}")

    def kill(self):
        if self.proc:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait(timeout=10)
            self.proc = None

    def http(self, path):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{self.http_port}{path}", timeout=10) as r:
            return json.loads(r.read())


@pytest.mark.timeout(600)
def test_three_process_network(tmp_path):
    nodes = [Node(i, tmp_path) for i in range(3)]
    procs = []
    try:
        for n in nodes:
            n.generate_keypair()
            n.start()
            procs.append(n.proc)

        secret_file = tmp_path / "secret"
        secret_file.write_text(SECRET)

        # run the DKG: leader + 2 followers, via the control plane
        leader_cmd = [
            "share", "--control", str(nodes[0].ctl_port), "--leader",
            "--nodes", "3", "--threshold", "2", "--period", str(PERIOD),
            "--secret-file", str(secret_file), "--timeout", "30"]
        follower_cmds = [
            ["share", "--control", str(n.ctl_port), "--connect",
             nodes[0].addr, "--secret-file", str(secret_file),
             "--timeout", "30"]
            for n in nodes[1:]]
        ps = [subprocess.Popen(
            [sys.executable, "-m", "drand_tpu.cli", *cmd],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=cli_env(), cwd=REPO)
            for cmd in [leader_cmd] + follower_cmds]
        outs = [p.communicate(timeout=180) for p in ps]
        for p, (so, se) in zip(ps, outs):
            assert p.returncode == 0, f"share failed: {so}\n{se}"
        group = json.loads(outs[0][0])["group"]
        assert group["threshold"] == 2 and len(group["nodes"]) == 3

        # wait for beacons over the public HTTP API
        deadline = time.time() + 120
        latest = None
        while time.time() < deadline:
            try:
                latest = nodes[0].http("/public/latest")
                if latest["round"] >= 2:
                    break
            except Exception:
                pass
            time.sleep(1)
        assert latest and latest["round"] >= 2, "no beacons over HTTP"

        # all three agree and the beacon verifies via the CLI client
        r = latest["round"]
        vals = [n.http(f"/public/{r}")["randomness"] for n in nodes]
        assert vals[0] == vals[1] == vals[2]
        got = run_cli(["get", "public", "--url",
                       f"http://127.0.0.1:{nodes[0].http_port}",
                       "--round", str(r)])
        assert got.returncode == 0, got.stderr
        assert json.loads(got.stdout)["randomness"] == vals[0]

        info = nodes[0].http("/info")
        assert info["period"] == PERIOD

        # kill node 2; the 2-of-3 chain must keep going
        nodes[2].kill()
        r_before = nodes[0].http("/public/latest")["round"]
        deadline = time.time() + 60
        while time.time() < deadline:
            if nodes[0].http("/public/latest")["round"] >= r_before + 2:
                break
            time.sleep(1)
        assert nodes[0].http("/public/latest")["round"] >= r_before + 2, \
            "chain stalled after killing one node"

        # restart node 2 from disk: it must catch up and serve the chain
        nodes[2].start()
        deadline = time.time() + 60
        tip = nodes[0].http("/public/latest")["round"]
        caught_up = False
        while time.time() < deadline:
            try:
                if nodes[2].http("/public/latest")["round"] >= tip:
                    caught_up = True
                    break
            except Exception:
                pass
            time.sleep(1)
        assert caught_up, "restarted node did not catch up"

        # clean shutdown via control
        for n in nodes:
            out = run_cli(["stop", "--control", str(n.ctl_port)], timeout=30)
            assert out.returncode == 0, out.stderr
    finally:
        for n in nodes:
            try:
                n.kill()
            except Exception:
                pass
