"""Concurrency-safety analysis tier (ISSUE 13): lockheld, threadshare
and awaitatomic fixtures, the call-graph decorator fix, the runner
satellites (SARIF, --prune-baseline), and regression tests for every
live race the passes caught — thread hammers for the fixed
warn-once/warm-shape globals, interleaving proofs for the fixed
check-then-act caches.

Late-alphabet filename per the tier-1 chunking convention
(tools/tier1_chunks.sh). Host-only: pure AST plus thread/event-loop
harnesses — no device graphs, no backend init, no fresh XLA compiles.
"""

import asyncio
import json
import textwrap
import threading
import time

import pytest

from tools.analyze import awaitatomic, lockheld, loopblock, threadshare
from tools.analyze.core import Project
from tools.analyze.run import (REPO, prune_baseline, run_analysis,
                               to_sarif, write_sarif)

# ---------------------------------------------------------------------------
# fixture helpers
# ---------------------------------------------------------------------------


def _tree(tmp_path, files: dict) -> str:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _project(tmp_path, files: dict) -> Project:
    return Project(_tree(tmp_path, files))


# ---------------------------------------------------------------------------
# lockheld
# ---------------------------------------------------------------------------


def test_lockheld_await_and_pairing_under_lock(tmp_path):
    """A threading lock held across an await or across pairing-class
    work is high; releasing before the await, and an `async with` on an
    asyncio lock, are clean."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def verify_beacons(pub, beacons):
                return [True] * len(beacons)
        """,
        "app/svc.py": """
            import asyncio
            import threading
            from drand_tpu.crypto import batch

            class Holder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._aio_lock = asyncio.Lock()
                    self._items = []

                async def bad_await(self, peer):
                    with self._lock:
                        data = await peer.fetch()
                        self._items.append(data)

                def bad_pairing(self, pub, chunk):
                    with self._lock:
                        return batch.verify_beacons(pub, chunk)

                async def bad_handoff(self, pub, chunk):
                    with self._lock:
                        return await asyncio.to_thread(
                            batch.verify_beacons, pub, chunk)

                async def good_narrow(self, peer):
                    data = await peer.fetch()
                    with self._lock:
                        self._items.append(data)

                async def good_asyncio_lock(self, peer):
                    async with self._aio_lock:
                        return await peer.fetch()
        """,
    })
    findings = lockheld.run(proj)
    got = {(f.symbol.rsplit(".", 1)[-1], f.rule) for f in findings}
    assert ("bad_await", "lock-across-await") in got
    assert ("bad_pairing", "lock-over-pairing") in got
    assert ("bad_handoff", "lock-across-await") in got
    assert ("bad_handoff", "lock-across-handoff") in got
    names = {s for s, _ in got}
    assert "good_narrow" not in names
    assert "good_asyncio_lock" not in names
    assert all(f.severity == "high" for f in findings)
    assert all("_lock" in f.message for f in findings)


def test_lockheld_transitive_taint_through_helper(tmp_path):
    """The pass reuses loopblock's fixpoint: a call made under the lock
    that only reaches the pairing leaf through a sync helper still
    counts."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def aggregate_round(pub, msg, parts, t, n):
                return [True], b"sig"
        """,
        "app/agg.py": """
            import threading
            from drand_tpu.crypto import batch

            _LOCK = threading.Lock()

            def helper(pub, msg, parts):
                return batch.aggregate_round(pub, msg, parts, 2, 3)

            def bad(pub, msg, parts):
                with _LOCK:
                    return helper(pub, msg, parts)
        """,
    })
    findings = lockheld.run(proj)
    assert [f.symbol for f in findings] == ["app.agg.bad"]
    assert findings[0].rule == "lock-over-pairing"
    assert "_LOCK" in findings[0].key


def test_lockheld_real_tree_only_engine_singleton():
    """The live tree holds exactly one reviewed lock-across-blocking
    site: the double-checked engine-singleton init (baselined with a
    written reason — releasing the lock there would double-construct
    the engine)."""
    proj = Project(REPO, packages=("drand_tpu",))
    findings = lockheld.run(proj)
    assert [f.symbol for f in findings] == ["drand_tpu.crypto.batch.engine"]


# ---------------------------------------------------------------------------
# threadshare
# ---------------------------------------------------------------------------


def _dual_ctx_files(guarded: bool) -> dict:
    """A module-global mutated from a to_thread worker AND read from
    the loop — the exact shape of the batch.py warn-once bug the pass
    caught live (``_FALLBACK_LOGGED``)."""
    lock_line = "with _STATE_LOCK:\n        _WARNED = True" if guarded \
        else "_WARNED = True"
    return {
        "app/disp.py": f"""
            import asyncio
            import threading

            _STATE_LOCK = threading.Lock()
            _WARNED = False

            def note_failure():
                global _WARNED
                if not _WARNED:
                    {lock_line}

            def heavy_work(x):
                note_failure()
                return x

            async def handler(x):
                # loop side reads the flag via the same helper
                note_failure()
                return await asyncio.to_thread(heavy_work, x)
        """,
    }


def test_threadshare_flags_dual_context_global(tmp_path):
    proj = _project(tmp_path, _dual_ctx_files(guarded=False))
    findings = threadshare.run(proj)
    assert [(f.rule, f.detail) for f in findings] == \
        [("unlocked-global-mutation", "_WARNED")]
    assert findings[0].severity == "high"
    assert "BOTH the event loop and to_thread workers" in findings[0].message


def test_threadshare_lock_guard_vouches(tmp_path):
    proj = _project(tmp_path, _dual_ctx_files(guarded=True))
    assert threadshare.run(proj) == []


def test_threadshare_self_attr_and_lock_covered_helper(tmp_path):
    """Self-attribute mutations on a dual-context class are high unless
    the mutation is under the class lock — or in a helper the public
    methods only ever call UNDER the lock (the FlightRecorder._get
    idiom: _lock-guarded-by-construction types vouch themselves)."""
    proj = _project(tmp_path, {
        "app/rec.py": """
            import asyncio
            import threading

            class Recorder:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._rounds = {}
                    self._peers = {}

                def _get(self, r):
                    # mutates WITHOUT taking the lock itself...
                    rec = self._rounds.get(r)
                    if rec is None:
                        rec = self._rounds[r] = {"events": []}
                    return rec

                def note(self, r, ev):
                    with self._lock:
                        # ...but every call site holds it: vouched
                        self._get(r)["events"].append(ev)

                def bad_note_peer(self, idx):
                    self._peers[idx] = True  # unlocked mutation

                async def loop_reader(self, r):
                    with self._lock:
                        return dict(self._rounds.get(r) or {})

                async def loop_peers(self):
                    return dict(self._peers)

                def worker(self, r, ev, idx):
                    self.note(r, ev)
                    self.bad_note_peer(idx)

                async def ingest(self, r, ev):
                    await asyncio.to_thread(self.worker, r, ev, 1)
                    self.note(r, ev)
                    self.bad_note_peer(2)
                    await self.loop_reader(r)
                    await self.loop_peers()
        """,
    })
    findings = threadshare.run(proj)
    assert [(f.symbol.rsplit(".", 1)[-1], f.detail) for f in findings] == \
        [("bad_note_peer", "_peers")]
    assert findings[0].rule == "unlocked-shared-mutation"
    assert findings[0].severity == "high"


def test_threadshare_single_context_mutation_is_clean(tmp_path):
    """Loop-only state needs no lock: without a thread-side toucher the
    same unlocked mutation is not a finding (the ChainStore.cache /
    Handler pattern — loop-confined by construction)."""
    proj = _project(tmp_path, {
        "app/loop_only.py": """
            class Collector:
                def __init__(self):
                    self._rounds = {}

                def append(self, r, p):
                    self._rounds.setdefault(r, []).append(p)

            async def ingest(c, r, p):
                c.append(r, p)

            async def serve(c, r):
                return list(c._rounds.get(r, ()))
        """,
    })
    assert threadshare.run(proj) == []


def test_threadshare_real_tree_chain_engine_is_loop_confined():
    """The ISSUE expected findings in chain/engine/ — the passes proved
    the collector plane is loop-confined instead (every PartialCache /
    Handler / ChainStore mutation happens on the loop; only the
    pairing work itself is handed to threads, by value). Pin that
    invariant: none of their attributes may become dual-context without
    a lock showing up here as a finding."""
    proj = Project(REPO, packages=("drand_tpu",))
    _, _, dual_attrs, _, _ = threadshare.analyze(proj)
    for cls in ("drand_tpu.chain.engine.cache.PartialCache",
                "drand_tpu.chain.engine.cache.RoundCache",
                "drand_tpu.chain.engine.chain_store.ChainStore",
                "drand_tpu.chain.engine.handler.Handler"):
        shared = {a for c, a in dual_attrs if c == cls}
        assert not shared, f"{cls} attrs went dual-context: {shared}"
    assert threadshare.run(proj) == []


# ---------------------------------------------------------------------------
# awaitatomic
# ---------------------------------------------------------------------------


def test_awaitatomic_check_then_act_and_recheck_fix(tmp_path):
    """The TOCTOU cache shape is flagged; the documented re-check fix
    and a branch that writes BEFORE its first await are clean."""
    proj = _project(tmp_path, {
        "app/cachemod.py": """
            class C:
                async def bad(self):
                    if self._info is None:
                        self._info = await self.fetch()
                    return self._info

                async def bad_multiline(self, key):
                    if key not in self._cache:
                        val = await self.compute(key)
                        self._cache[key] = val
                    return self._cache[key]

                async def good_recheck(self):
                    if self._info is None:
                        got = await self.fetch()
                        if self._info is None:
                            self._info = got
                    return self._info

                async def good_write_before_await(self):
                    if self._busy is False:
                        self._busy = True
                        await self.work()
                    return self._busy
        """,
    })
    findings = awaitatomic.run(proj)
    got = {(f.symbol.rsplit(".", 1)[-1], f.detail) for f in findings}
    assert got == {("bad", "_info"), ("bad_multiline", "_cache")}
    assert all(f.severity == "medium" for f in findings)
    assert all(f.rule == "check-then-act" for f in findings)


def test_awaitatomic_async_lock_suppresses(tmp_path):
    """A check-then-act serialized by an asyncio lock (async with) is
    correct — tasks can no longer interleave between check and act."""
    proj = _project(tmp_path, {
        "app/locked.py": """
            class C:
                async def good(self):
                    async with self._info_lock:
                        if self._info is None:
                            self._info = await self.fetch()
                    return self._info
        """,
    })
    assert awaitatomic.run(proj) == []


def test_awaitatomic_escalates_thread_shared(tmp_path):
    """Medium becomes HIGH when the attribute is also touched from
    worker threads (threadshare's dual-context map): then the stale
    check races OS threads, not just cooperative tasks."""
    proj = _project(tmp_path, {
        "app/svc.py": """
            import asyncio

            class S:
                def worker(self):
                    return self._conn.query()

                async def bad(self):
                    if self._conn is None:
                        self._conn = await self.dial()
                    return await asyncio.to_thread(self.worker)
        """,
    })
    findings = awaitatomic.run(proj)
    assert [(f.rule, f.severity, f.detail) for f in findings] == \
        [("check-then-act-threaded", "high", "_conn")]
    assert "threadshare" in findings[0].message


def test_awaitatomic_project_shaped_timelock_fixture(tmp_path):
    """Project-shaped fixture reproducing the live TimelockService.info
    finding (fixed in this PR with the re-check idiom): the pre-fix
    shape is a finding, the shipped shape is clean."""
    before = _project(tmp_path / "before", {
        "drand_tpu/timelock/service.py": """
            class TimelockService:
                async def info(self):
                    if self._info is None:
                        self._info = await self._client.info()
                    return self._info
        """,
    })
    findings = awaitatomic.run(before)
    assert [(f.symbol, f.detail) for f in findings] == \
        [("drand_tpu.timelock.service.TimelockService.info", "_info")]

    after = _project(tmp_path / "after", {
        "drand_tpu/timelock/service.py": """
            class TimelockService:
                async def info(self):
                    if self._info is None:
                        got = await self._client.info()
                        if self._info is None:
                            self._info = got
                    return self._info
        """,
    })
    assert awaitatomic.run(after) == []


def test_awaitatomic_real_tree_clean():
    proj = Project(REPO, packages=("drand_tpu",))
    assert awaitatomic.run(proj) == []


# ---------------------------------------------------------------------------
# call-graph decorator fix (ISSUE 13 satellite)
# ---------------------------------------------------------------------------


def test_decorated_async_def_reaching_pairing_leaf_is_caught(tmp_path):
    """A functools.wraps-style decorated async def reaching a pairing
    leaf is flagged — decoration must not hide the path."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def verify_beacons(pub, beacons):
                return [True] * len(beacons)
        """,
        "app/svc.py": """
            import functools
            from drand_tpu.crypto import batch

            def logged(f):
                @functools.wraps(f)
                async def inner(*a, **k):
                    return await f(*a, **k)
                return inner

            @logged
            async def handler(pub, chunk):
                return batch.verify_beacons(pub, chunk)
        """,
    })
    findings = loopblock.run(proj)
    assert any(f.symbol == "app.svc.handler" and f.severity == "high"
               for f in findings)


def test_decorator_wrapper_body_taints_decorated_calls(tmp_path):
    """The fixed blind spot: calling a decorated function executes the
    WRAPPER's body too. A decorator that sleeps (or locks) around every
    call it wraps now taints async callers of the decorated name."""
    proj = _project(tmp_path, {
        "app/deco.py": """
            import functools
            import time

            def throttled(f):
                @functools.wraps(f)
                def inner(*a, **k):
                    time.sleep(0.2)
                    return f(*a, **k)
                return inner

            @throttled
            def lookup(key):
                return key

            async def handler(key):
                return lookup(key)
        """,
    })
    findings = loopblock.run(proj)
    bad = [f for f in findings if f.symbol == "app.deco.handler"]
    assert len(bad) == 1 and "time.sleep" in bad[0].message


def test_decorator_wrapper_lock_held_across_wrapped_pairing(tmp_path):
    """lockheld sees through the decoration too: a pass-through wrapper
    that holds a lock while invoking the wrapped function is a
    lock-over-pairing finding once any wrapped function is
    pairing-class."""
    proj = _project(tmp_path, {
        "drand_tpu/crypto/batch.py": """
            def verify_beacons(pub, beacons):
                return [True] * len(beacons)
        """,
        "app/deco.py": """
            import functools
            import threading

            _LOCK = threading.Lock()

            def serialized(f):
                @functools.wraps(f)
                def inner(*a, **k):
                    with _LOCK:
                        return f(*a, **k)
                return inner

            @serialized
            def verify(pub, chunk):
                from drand_tpu.crypto import batch

                return batch.verify_beacons(pub, chunk)
        """,
    })
    findings = lockheld.run(proj)
    assert [f.symbol for f in findings] == \
        ["app.deco.serialized.inner"]
    assert findings[0].rule == "lock-over-pairing"


# ---------------------------------------------------------------------------
# baseline round-trip + prune for the new pass names
# ---------------------------------------------------------------------------


def test_new_passes_baseline_roundtrip_and_prune(tmp_path):
    """A lockheld/awaitatomic finding suppresses through the baseline
    like any other; fixing the code flags the entry stale; and
    --prune-baseline drops ONLY entries whose pass ran, preserving the
    written reasons of everything kept."""
    root = _tree(tmp_path, {
        "app/svc.py": """
            import threading

            class S:
                _lock = threading.Lock()

                async def held(self, peer):
                    with self._lock:
                        return await peer.call()

                async def cachey(self):
                    if self._v is None:
                        self._v = await self.f()
                    return self._v
        """,
    })
    passes = ("lockheld", "awaitatomic")
    report = run_analysis(root=root, passes=passes,
                          baseline_path=tmp_path / "missing.json")
    keys = sorted(f.key for f in report["findings"])
    assert len(keys) == 2
    assert keys[0].startswith("awaitatomic:check-then-act:")
    assert keys[1].startswith("lockheld:lock-across-await:")

    bl = tmp_path / "baseline.json"
    entries = [{"key": k, "reason": f"fixture: reviewed entry {i}"}
               for i, k in enumerate(keys)]
    entries.append({"key": "jaxhazard:gone:app/x.py:app.x.f",
                    "reason": "fixture: pass not run, must survive prune"})
    bl.write_text(json.dumps({"entries": entries}))

    report = run_analysis(root=root, passes=passes, baseline_path=bl)
    assert report["findings"] == []
    assert sorted(f.key for f in report["suppressed"]) == keys

    # fix the lockheld site -> its entry goes stale, prune drops it
    (tmp_path / "app" / "svc.py").write_text(textwrap.dedent("""
        import threading

        class S:
            _lock = threading.Lock()

            async def held(self, peer):
                return await peer.call()

            async def cachey(self):
                if self._v is None:
                    self._v = await self.f()
                return self._v
    """))
    report = run_analysis(root=root, passes=passes, baseline_path=bl)
    assert any(f.rule == "stale-entry" for f in report["findings"])
    dropped, kept = prune_baseline(report, passes, bl)
    assert dropped == [keys[1]]
    assert kept == 2
    doc = json.loads(bl.read_text())
    kept_keys = [e["key"] for e in doc["entries"]]
    assert keys[0] in kept_keys                      # still matching
    assert "jaxhazard:gone:app/x.py:app.x.f" in kept_keys  # pass not run
    assert doc["entries"][0]["reason"].startswith("fixture:")

    # post-prune the tree round-trips clean (no stale entries left
    # for the passes that ran)
    report = run_analysis(root=root, passes=passes, baseline_path=bl)
    assert [f.rule for f in report["findings"]] == []


def test_sarif_output_shape(tmp_path):
    """--sarif: findings as SARIF 2.1.0 with severity mapped to level
    and the baseline key as a stable fingerprint."""
    root = _tree(tmp_path, {
        "app/svc.py": """
            import threading

            class S:
                _lock = threading.Lock()

                async def held(self, peer):
                    with self._lock:
                        return await peer.call()
        """,
    })
    report = run_analysis(root=root, passes=("lockheld",),
                          baseline_path=tmp_path / "missing.json")
    doc = to_sarif(report)
    assert doc["version"] == "2.1.0"
    run = doc["runs"][0]
    assert run["tool"]["driver"]["name"] == "drand-tpu-analyze"
    (res,) = run["results"]
    assert res["ruleId"] == "lockheld/lock-across-await"
    assert res["level"] == "error"
    loc = res["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "app/svc.py"
    assert loc["region"]["startLine"] >= 1
    assert res["partialFingerprints"]["drandAnalyzeKey/v1"] == \
        report["findings"][0].key
    rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
    assert rule_ids == ["lockheld/lock-across-await"]

    out = tmp_path / "out.sarif"
    write_sarif(report, out)
    assert json.loads(out.read_text())["version"] == "2.1.0"


# ---------------------------------------------------------------------------
# thread hammers: the fixed shared state survives real contention
# ---------------------------------------------------------------------------


def _hammer(n_threads: int, fn) -> None:
    barrier = threading.Barrier(n_threads)
    errs = []

    def runner():
        barrier.wait()
        try:
            for _ in range(200):
                fn()
        except Exception as e:  # noqa: BLE001 — surface in the test
            errs.append(e)

    threads = [threading.Thread(target=runner) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert errs == []


def _hist_count(metric, **labels) -> float:
    for family in metric.collect():
        for s in family.samples:
            if s.name.endswith("_count") and all(
                    s.labels.get(k) == v for k, v in labels.items()):
                return s.value
    return 0.0


def test_hammer_warm_shapes_single_compile_sample():
    """4 threads racing the same cold (op, path, batch) shape through
    the _timed compile split: exactly ONE dispatch claims the
    engine_compile_seconds sample; every other lands in
    engine_op_seconds (pre-fix, every racer could claim it and the
    steady-state series silently lost their samples)."""
    from drand_tpu import metrics
    from drand_tpu.crypto import batch

    key_op = "verify_beacons"
    with batch._STATE_LOCK:
        batch._WARM_SHAPES.clear()
    before_compile = _hist_count(metrics.ENGINE_COMPILE_SECONDS, op=key_op)
    before_ops = _hist_count(metrics.ENGINE_OP_SECONDS, op=key_op,
                             path="device")

    def one():
        with batch._timed(key_op, "device", 64):
            pass

    _hammer(4, one)
    compiles = _hist_count(metrics.ENGINE_COMPILE_SECONDS,
                           op=key_op) - before_compile
    ops = _hist_count(metrics.ENGINE_OP_SECONDS, op=key_op,
                      path="device") - before_ops
    assert compiles == 1.0
    assert ops == 4 * 200 - 1
    with batch._STATE_LOCK:
        batch._WARM_SHAPES.clear()


def test_hammer_fallback_warn_once_and_rearm(monkeypatch):
    """4 threads hammering _note_fallback warn exactly once; a device
    success re-arms, and the next failure burst warns exactly once
    again."""
    from drand_tpu.crypto import batch
    from drand_tpu.utils import logging as dlog

    warns = []

    class _L:
        def warn(self, *a, **k):
            warns.append((a, k))

    monkeypatch.setattr(dlog, "default_logger", lambda name: _L())
    batch._note_device_ok()  # known re-armed start state
    _hammer(4, lambda: batch._note_fallback("verify_beacons",
                                            RuntimeError("boom")))
    assert len(warns) == 1
    batch._note_device_ok()
    _hammer(4, lambda: batch._note_fallback("verify_beacons",
                                            RuntimeError("boom2")))
    assert len(warns) == 2
    batch._note_device_ok()


def test_hammer_ecies_warn_once(monkeypatch):
    from drand_tpu.crypto import ecies
    from drand_tpu.utils import logging as dlog

    warns = []

    class _L:
        def warn(self, *a, **k):
            warns.append(a)

    monkeypatch.setattr(dlog, "default_logger", lambda name: _L())
    monkeypatch.setattr(ecies, "_FALLBACK_WARNED", False)
    _hammer(4, ecies._warn_fallback)
    assert len(warns) == 1


def test_hammer_probe_bg_spawns_one_probe(monkeypatch):
    """4 threads racing probe_backend_bg launch exactly one probe
    thread (pre-fix, every racer could spawn a subprocess probe and
    clobber _PROBE_THREAD, breaking the join-in-flight path)."""
    from drand_tpu.utils import backend

    started = []
    release = threading.Event()

    def fake_probe(timeout=90.0, cache=True):
        started.append(threading.current_thread())
        release.wait(10)
        with backend._VERDICT_LOCK:
            backend._PROBE_RESULT = False
            backend._PROBE_TIME = time.monotonic()
        return False

    monkeypatch.setattr(backend, "probe_backend", fake_probe)
    monkeypatch.setattr(backend, "_PROBE_RESULT", None)
    monkeypatch.setattr(backend, "_PROBE_TIME", 0.0)
    monkeypatch.setattr(backend, "_PROBE_THREAD", None)
    try:
        _hammer(4, backend.probe_backend_bg)
        assert len(started) == 1
        th = backend._PROBE_THREAD
        assert th is not None and th in started
    finally:
        release.set()
        if backend._PROBE_THREAD is not None:
            backend._PROBE_THREAD.join(10)
        monkeypatch.setattr(backend, "_PROBE_RESULT", None)
        monkeypatch.setattr(backend, "_PROBE_THREAD", None)


# ---------------------------------------------------------------------------
# interleaving regressions for the fixed check-then-act caches
# ---------------------------------------------------------------------------


@pytest.mark.asyncio
async def test_timelock_info_first_publication_wins():
    """Two tasks race TimelockService.info() on a cold cache: both
    fetch, but the loser's result must not clobber the published one —
    both callers observe the SAME object (pre-fix each caller published
    its own fetch, so concurrent users held different Info objects and
    a slow fetch overwrote the one in active use)."""
    from drand_tpu.timelock.service import TimelockService
    from drand_tpu.timelock.vault import TimelockVault

    gate = asyncio.Event()
    fetched = []

    class _Client:
        async def info(self):
            obj = object()
            fetched.append(obj)
            await gate.wait()
            return obj

    vault = TimelockVault(":memory:")
    try:
        svc = TimelockService(vault, _Client())
        t1 = asyncio.ensure_future(svc.info())
        t2 = asyncio.ensure_future(svc.info())
        for _ in range(50):
            await asyncio.sleep(0)
            if len(fetched) == 2:
                break
        assert len(fetched) == 2  # both raced past the cold check
        gate.set()
        r1, r2 = await asyncio.gather(t1, t2)
        assert r1 is r2
        assert svc._info is r1
        assert await svc.info() is r1  # stable afterwards
    finally:
        vault.close()


@pytest.mark.asyncio
async def test_otlp_session_rebuild_is_single_flight(monkeypatch):
    """Two tasks hit _get_session while the cached session belongs to a
    dead loop: exactly ONE replacement is built (pre-fix both built
    one and the loser's ClientSession leaked unclosed forever)."""
    import aiohttp

    from drand_tpu.obs.export import OTLPExporter

    created = []

    class _FakeSession:
        def __init__(self, *a, **k):
            created.append(self)
            self.closed = False

        async def close(self):
            await asyncio.sleep(0.01)  # the suspension the race needs
            self.closed = True

    monkeypatch.setattr(aiohttp, "ClientSession", _FakeSession)
    exp = OTLPExporter(endpoint="http://collector:4318")
    stale = _FakeSession()
    created.clear()
    exp._session = stale
    exp._session_loop = object()  # "a previous event loop"

    s1, s2 = await asyncio.gather(exp._get_session(),
                                  exp._get_session())
    assert s1 is s2
    assert len(created) == 1
    assert stale.closed  # the old session was actually closed
    assert exp._session is s1


# ---------------------------------------------------------------------------
# the real tree, whole-suite
# ---------------------------------------------------------------------------


def test_real_tree_concurrency_passes_clean_and_fast():
    """The acceptance gate: all three concurrency passes run on the
    live tree with zero unsuppressed findings (the one lockheld finding
    carries a reviewed baseline entry), inside the host-only time
    budget (<10 s nominal; the bound here is padded for the contended
    1-core CI box)."""
    t0 = time.perf_counter()
    report = run_analysis(passes=("lockheld", "threadshare",
                                  "awaitatomic"))
    elapsed = time.perf_counter() - t0
    assert report["findings"] == [], "\n".join(
        f.render() for f in report["findings"])
    assert [f.pass_name for f in report["suppressed"]] == ["lockheld"]
    assert elapsed < 30.0
