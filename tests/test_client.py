"""Client library tests: verifying stack, strict catch-up, V1/V2
switchover, caching, optimizing failover, watch aggregation.

Reference coverage model: client/client_test.go, client/verify.go:115-209,
client/cache_test.go, client/optimizing_test.go — against a live
in-process beacon network (no mocks for the happy path, a corrupting
wrapper for the negative paths).
"""

import asyncio

import pytest

from drand_tpu.client import (
    CachingClient,
    ClientError,
    DirectClient,
    OptimizingClient,
    new_client,
)
from drand_tpu.client.interface import Client, Result
from drand_tpu.testing.harness import BeaconTestNetwork

N, T, PERIOD = 3, 2, 5


async def make_net(rounds=4):
    net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(rounds):
        await net.clock.advance(PERIOD)
    for i in range(N):
        await net.wait_round(i, rounds)
    return net


@pytest.mark.asyncio
async def test_get_verified_and_cached():
    net = await make_net()
    try:
        src = DirectClient(net.nodes[0].handler)
        info = await src.info()
        client = new_client([src], chain_info=info)
        r3 = await client.get(3)
        assert r3.round == 3 and len(r3.randomness) == 32
        latest = await client.get()
        assert latest.round >= 3
        # cache hit returns the same object
        again = await client.get(3)
        assert again is r3
    finally:
        net.stop_all()


@pytest.mark.asyncio
async def test_chain_hash_pinning():
    net = await make_net(rounds=1)
    try:
        src = DirectClient(net.nodes[0].handler)
        info = await src.info()
        good = new_client([src], chain_hash=info.hash())
        assert (await good.get(1)).round == 1
        bad = new_client([src], chain_hash=b"\x13" * 32)
        with pytest.raises(ClientError):
            await bad.get(1)
    finally:
        net.stop_all()


class CorruptingSource(Client):
    """Wraps a source, corrupting the signature of one round."""

    def __init__(self, src, bad_round, field="signature"):
        self._src = src
        self._bad = bad_round
        self._field = field

    async def get(self, round_no=0):
        r = await self._src.get(round_no)
        if r.round == self._bad:
            setattr(r, self._field,
                    bytes([getattr(r, self._field)[0] ^ 1]) +
                    getattr(r, self._field)[1:])
        return r

    async def info(self):
        return await self._src.info()

    def watch(self):
        return self._src.watch()

    def round_at(self, t):
        return self._src.round_at(t)


@pytest.mark.asyncio
async def test_corrupted_beacon_rejected():
    net = await make_net()
    try:
        src = CorruptingSource(DirectClient(net.nodes[0].handler), bad_round=2)
        info = await net_info(net)
        client = new_client([src], chain_info=info)
        assert (await client.get(3)).round == 3
        with pytest.raises(ClientError):
            await client.get(2)
    finally:
        net.stop_all()


@pytest.mark.asyncio
async def test_strict_rounds_catchup_detects_history_corruption():
    """Strict mode walks the chain from genesis in batched chunks; a
    corrupted historical round must poison the walk (verify.go:146-163)."""
    net = await make_net(rounds=5)
    try:
        info = await net_info(net)
        good = new_client([DirectClient(net.nodes[0].handler)],
                          chain_info=info, strict_rounds=True)
        r5 = await good.get(5)
        assert r5.round == 5
        bad_src = CorruptingSource(DirectClient(net.nodes[1].handler),
                                   bad_round=2)
        bad = new_client([bad_src], chain_info=info, strict_rounds=True)
        with pytest.raises(ClientError):
            await bad.get(5)
    finally:
        net.stop_all()


@pytest.mark.asyncio
async def test_v1_v2_switchover():
    """Rounds past v1_verification_until verify via the unchained V2
    signature only — a corrupted V1 signature no longer matters, but a
    corrupted V2 one fails (client/client.go:367, verify.go:176-209)."""
    net = await make_net(rounds=4)
    try:
        info = await net_info(net)
        # corrupt V1 signature of round 4: V2-era verification ignores it...
        v1_corrupt = CorruptingSource(DirectClient(net.nodes[0].handler),
                                      bad_round=4, field="signature")
        client = new_client([v1_corrupt], chain_info=info,
                            v1_verification_until=3)
        r = await client.get(4)
        assert r.round == 4
        # ...but the same corruption fails a pre-switchover round
        v1_corrupt_old = CorruptingSource(DirectClient(net.nodes[0].handler),
                                          bad_round=2, field="signature")
        client2 = new_client([v1_corrupt_old], chain_info=info,
                             v1_verification_until=3)
        with pytest.raises(ClientError):
            await client2.get(2)
        # and corrupting V2 fails a post-switchover round
        v2_corrupt = CorruptingSource(DirectClient(net.nodes[0].handler),
                                      bad_round=4, field="signature_v2")
        client3 = new_client([v2_corrupt], chain_info=info,
                             v1_verification_until=3)
        with pytest.raises(ClientError):
            await client3.get(4)
    finally:
        net.stop_all()


class FailingSource(Client):
    def __init__(self, src, fail_times=10**9):
        self._src = src
        self._fails_left = fail_times

    async def get(self, round_no=0):
        if self._fails_left > 0:
            self._fails_left -= 1
            raise ClientError("synthetic failure")
        return await self._src.get(round_no)

    async def info(self):
        return await self._src.info()

    def watch(self):
        return self._src.watch()

    def round_at(self, t):
        return self._src.round_at(t)


@pytest.mark.asyncio
async def test_optimizing_failover():
    net = await make_net(rounds=2)
    try:
        healthy = DirectClient(net.nodes[0].handler)
        failing = FailingSource(DirectClient(net.nodes[1].handler))
        opt = OptimizingClient([failing, healthy], request_timeout=1.0)
        r = await opt.get(2)
        assert r.round == 2
        # the failing source was demoted to the back
        assert opt._sources[0] is healthy
    finally:
        net.stop_all()


@pytest.mark.asyncio
async def test_watch_aggregation_fanout():
    net = await make_net(rounds=1)
    try:
        info = await net_info(net)
        client = new_client([DirectClient(net.nodes[0].handler)],
                            chain_info=info)

        async def take_one(stream):
            async for r in stream:
                return r

        w1 = asyncio.ensure_future(take_one(client.watch()))
        w2 = asyncio.ensure_future(take_one(client.watch()))
        await asyncio.sleep(0.05)  # let subscriptions register
        last = net.nodes[0].handler.chain.last().round
        await net.clock.advance(PERIOD)
        for i in range(N):
            await net.wait_round(i, last + 1)
        r1, r2 = await asyncio.wait_for(asyncio.gather(w1, w2), timeout=10)
        assert r1.round == r2.round >= last + 1
        assert r1.randomness == r2.randomness
    finally:
        net.stop_all()


async def net_info(net):
    return await DirectClient(net.nodes[0].handler).info()
