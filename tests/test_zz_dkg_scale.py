"""Large-group ceremony batching (ISSUE 19).

Late-alphabet like the other scale suites: the structural harness
patches module leaves (testing/dkg_scale.structural_dkg_crypto) and
FLIGHT's DKG ring is process-global, so these tests run after the
plain crypto suites in a chunk.

Two layers of proof:
- REAL crypto at small n: every batched phase verdict bit-identical
  to the per-item oracle it replaced — lockstep G1 membership vs
  ``in_subgroup``, ``parse_commits`` vs the sequential
  ``from_bytes(subgroup_check=True)`` loop, comb ``share_checks`` vs
  generator ladders, RLC ``reshare_bindings`` vs per-dealer Horner
  (full one-bad-dealer matrix).
- STRUCTURAL group at big n: the protocol machinery itself — n=64
  reshare excludes exactly the bad-constant-term dealer, n=48
  ceremony timelines land in the flight recorder, chunked deal
  admission still closes the response window under FakeClock, and
  every rejection is attributable (counter + flight note).
"""

import asyncio

import pytest

from drand_tpu import metrics
from drand_tpu.crypto import batch, ecies, endo
from drand_tpu.crypto.curves import PointG1
from drand_tpu.crypto.fields import Fp, R
from drand_tpu.crypto.poly import PriPoly, PubPoly
from drand_tpu.dkg import DKGConfig, DKGProtocol, LocalBoard
from drand_tpu.dkg.packets import Deal, DealBundle, Response, ResponseBundle
from drand_tpu.dkg.packets import STATUS_APPROVAL, STATUS_COMPLAINT
from drand_tpu.obs.flight import FLIGHT
from drand_tpu.testing import dkg_scale
from drand_tpu.utils.clock import FakeClock


@pytest.fixture(autouse=True)
def _host_mode():
    """Pin host dispatch: these tests prove host-path bit-identity (the
    engine KATs cover device-vs-host) and must not kick a backend
    probe mid-suite."""
    saved = batch._MODE
    batch.configure("host")
    yield
    batch.configure(saved)


def _order3_torsion() -> PointG1:
    """An explicit order-3 point (clear every factor but one 3 from a
    full-group point) — the cofactor component the membership check
    exists to reject. Mirrors crypto/endo._validate_g1."""
    from drand_tpu.crypto.curves import H1

    for xi in range(1, 64):
        x = Fp(xi)
        y = (x.square() * x + PointG1.B).sqrt()
        if y is None:
            continue
        t = PointG1.from_affine(x, y).mul(H1 * R // 3)
        if not t.is_infinity():
            return t
    raise AssertionError("no torsion point found")


# ---------------------------------------------------------------------------
# real crypto: batched verdicts == per-item oracles
# ---------------------------------------------------------------------------

def test_lockstep_subgroup_check_matches_oracle():
    g = PointG1.generator()
    torsion = _order3_torsion()
    pts = [g.mul(101 + k) for k in range(20)]
    pts[3] = torsion
    pts[9] = pts[9] + torsion        # subgroup + torsion mix
    pts[14] = PointG1.infinity()
    want = [p.in_subgroup() for p in pts]
    assert want.count(False) == 2    # the two torsion-tainted lanes
    assert endo.subgroup_check_fast_g1_many(pts) == want
    # short list → per-point fast-check path, same oracle
    small = [g.mul(7), torsion, PointG1.infinity()]
    assert endo.subgroup_check_fast_g1_many(small) == \
        [p.in_subgroup() for p in small]


def test_parse_commits_matches_sequential_from_bytes():
    g = PointG1.generator()
    torsion = _order3_torsion()
    good = [tuple(g.mul(17 * b + k + 1).to_bytes() for k in range(3))
            for b in range(5)]
    bad_encoding = (good[0][0], b"\x00" * 48, good[0][2])
    bad_subgroup = (good[1][0], torsion.to_bytes(), good[1][2])
    bundles = [good[0], bad_encoding, good[1], bad_subgroup,
               good[2], good[3], good[4]]  # 21 points → lockstep path

    def oracle(cs):
        try:
            return [PointG1.from_bytes(c, subgroup_check=True) for c in cs]
        except ValueError:
            return None

    want = [oracle(cs) for cs in bundles]
    got = batch.parse_commits(bundles)
    assert [x is None for x in got] == [x is None for x in want]
    for gs, ws in zip(got, want):
        if gs is not None:
            assert gs == ws


def test_share_checks_matches_generator_ladder():
    g = PointG1.generator()
    scalars = [5, R - 2, 0x5EED + 7, 1, R + 3]
    pairs = [(s, g.mul(s % R)) for s in scalars]
    pairs.append((42, g.mul(43)))  # one wrong expectation
    want = [g.mul(s % R) == exp for s, exp in pairs]
    assert want == [True] * 5 + [False]
    assert batch.share_checks(pairs) == want


def test_reshare_bindings_one_bad_dealer_matrix():
    """RLC 2-MSM verdicts bit-identical to the per-dealer Horner oracle
    on the all-good case and EVERY single-bad-dealer case (the PR-2
    bisection-oracle idiom: the combined check must bisect to exactly
    the poisoned leaf, never an innocent one)."""
    old = PriPoly([7, 11, 13]).commit()
    n = 12
    good = [(i, old.eval(i).value) for i in range(n)]
    g = PointG1.generator()

    def oracle(items):
        return [old.eval(i).value == q for i, q in items]

    assert batch._use_rlc(n)  # the path under test
    assert batch.reshare_bindings(old, good) == [True] * n
    for bad in range(n):
        items = list(good)
        items[bad] = (bad, good[bad][1] + g)
        want = oracle(items)
        assert want == [i != bad for i in range(n)]
        assert batch.reshare_bindings(old, items) == want


def test_eval_many_matches_eval():
    pri = PriPoly([3, 1, 4, 1, 5])
    idxs = [0, 5, 2, 63, 2]  # duplicates + out-of-order stay aligned
    assert [(s.index, s.value) for s in pri.eval_many(idxs)] == \
        [(i, pri.eval(i).value) for i in idxs]
    pub = pri.commit()
    assert [(s.index, s.value) for s in pub.eval_many(idxs)] == \
        [(i, pub.eval(i).value) for i in idxs]


# ---------------------------------------------------------------------------
# structural group at scale
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_structural_ceremony_n48_timeline():
    n, t = 48, 13
    FLIGHT.dkg.reset()
    with dkg_scale.structural_dkg_crypto():
        res = await dkg_scale.run_ceremony(n, t, nonce=b"zz-cer-48")
        for r in res:
            assert r.qual == list(range(n))
        dkg_scale.check_structural_consistency(res, t)
    tl = dkg_scale.phase_timeline(mode="dkg")
    assert set(tl) == {"deal", "response", "finish"}  # no complaints
    rec = next(r for r in FLIGHT.dkg.sessions() if r["done"])
    assert rec["qual"] == list(range(n))
    assert len(rec["bundles"]["deal"]) == n
    assert rec["rejects"] == []
    FLIGHT.dkg.reset()


@pytest.mark.asyncio
async def test_structural_reshare_n64_excludes_bad_dealer():
    """The reshare dual-group binding at n=64: ONE dealer reshares from
    a corrupted old share (constant term off by one) — the batched
    binding check excludes exactly that dealer, QUAL keeps everyone
    else, and the group key is preserved."""
    n, t = 64, 17
    FLIGHT.dkg.reset()
    pairs, nodes = dkg_scale.make_group(n, prefix="zz-rs64")
    with dkg_scale.structural_dkg_crypto():
        res = await dkg_scale.run_ceremony(
            n, t, nonce=b"zz-rs-base", pairs=pairs, nodes=nodes)
        key = res[0].commits[0]
        res2 = await dkg_scale.run_reshare(
            res, pairs, nodes, t_old=t, t_new=t, bad_dealers=(5,))
        for r in res2:
            assert 5 not in r.qual
            assert r.qual == [i for i in range(n) if i != 5]
        dkg_scale.check_structural_consistency(res2, t, expected_key=key)
    # the exclusion is attributable: binding_mismatch notes name dealer 5
    rejects = [x for s in FLIGHT.dkg.sessions() for x in s["rejects"]]
    assert rejects and all(
        r["issuer"] == 5 and r["verdict"] == "binding_mismatch"
        and r["phase"] == "deal" for r in rejects)
    FLIGHT.dkg.reset()


@pytest.mark.asyncio
async def test_chunked_admission_keeps_phase_window_fakeclock():
    """Regression for the chunked deal admission (n > _ADMIT_CHUNK →
    multiple on-loop slices with cooperative yields): with a crashed
    dealer the phases must still time out and close on the FakeClock —
    a starved phase clock would wedge the response window open and
    QUAL would never form."""
    from drand_tpu.dkg.protocol import _ADMIT_CHUNK

    n, t = 48, 13
    assert n > _ADMIT_CHUNK
    FLIGHT.dkg.reset()
    clock = FakeClock()
    pairs, nodes = dkg_scale.make_group(n, prefix="zz-fake48")
    boards = LocalBoard.make_group(n)
    with dkg_scale.structural_dkg_crypto():
        configs = [DKGConfig(longterm=pairs[i], nonce=b"zz-fake",
                             new_nodes=nodes, threshold=t, clock=clock,
                             phase_timeout=10, seed=b"zz-fake")
                   for i in range(n - 1)]  # dealer n-1 never runs

        async def drive():
            # settle to quiescence before each advance: 47 collectors ×
            # 47 bundles is thousands of loop iterations of sim-instant
            # work — moving time mid-drain would close the deal window
            # on a scheduling artifact, not on the protocol
            for _ in range(10):
                for _ in range(200):
                    await clock.settle()
                await clock.advance(10)

        gathered = asyncio.gather(*(DKGProtocol(c, b).run()
                                    for c, b in zip(configs, boards)))
        await asyncio.gather(gathered, drive())
        res = gathered.result()
    for r in res:
        assert r.qual == list(range(n - 1))
    dkg_scale.check_structural_consistency(res, t)
    # every retained timeline closed its response window on the clock
    for rec in FLIGHT.dkg.sessions():
        resp = [p for p in rec["phases"] if p["phase"] == "response"]
        assert resp and resp[0]["end_s"] is not None
    FLIGHT.dkg.reset()


# ---------------------------------------------------------------------------
# attributable rejections
# ---------------------------------------------------------------------------

def _reject_count(phase: str, verdict: str) -> float:
    return metrics.DKG_BUNDLE_REJECTS.labels(
        phase=phase, verdict=verdict)._value.get()


@pytest.mark.asyncio
async def test_deal_rejects_mint_counter_and_flight_note():
    n, t = 6, 3
    FLIGHT.dkg.reset()
    pairs, nodes = dkg_scale.make_group(n, prefix="zz-rej")
    with dkg_scale.structural_dkg_crypto():
        conf = DKGConfig(longterm=pairs[0], nonce=b"zz-rej",
                         new_nodes=nodes, threshold=t, seed=b"zz-rej")
        proto = DKGProtocol(conf, LocalBoard())
        proto._sid = FLIGHT.dkg.begin(
            conf.nonce, mode="dkg", n_dealers=n, n_receivers=n,
            threshold=t, now=0.0, tag="s0")

        def bundle_from(dealer: int, commits=None, share_val=None):
            poly = PriPoly([dealer + 2, 9, 4])
            if commits is None:
                commits = tuple(c.to_bytes()
                                for c in poly.commit().commits)
            val = poly.eval(0).value if share_val is None else share_val
            deals = (Deal(share_index=0, encrypted_share=ecies.encrypt(
                nodes[0].identity.key, val.to_bytes(32, "big"))),)
            return DealBundle(dealer_index=dealer, commits=commits,
                              deals=deals, session_id=conf.nonce)

        before = {(ph, v): _reject_count(ph, v) for ph, v in
                  [("deal", "wrong_threshold"), ("deal", "bad_point"),
                   ("deal", "bad_share"), ("response", "unknown_dealer")]}
        bundles = [
            bundle_from(0),                                      # good
            bundle_from(1, commits=(b"\x00" * 48,) * t),         # bad_point
            bundle_from(2, commits=(b"junk",) * (t - 1)),  # wrong_threshold
            bundle_from(3, share_val=12345),                     # bad_share
        ]
        await proto._process_deals(bundles)
        assert set(proto._valid_shares) == {0}
        assert set(proto._valid_commits) == {0, 3}  # bad share ≠ bad commit
        proto._process_response(ResponseBundle(
            share_index=2, responses=(
                Response(dealer_index=99, status=STATUS_COMPLAINT),
                Response(dealer_index=0, status=STATUS_APPROVAL)),
            session_id=conf.nonce), conf.dealers())
        assert proto._approvals[0] == {2}

    for (ph, v), b in before.items():
        assert _reject_count(ph, v) == b + 1, (ph, v)
    rec = next(r for r in FLIGHT.dkg.sessions()
               if r["session"].endswith("/s0"))
    got = {(x["phase"], x["issuer"], x["verdict"]) for x in rec["rejects"]}
    assert got == {("deal", 1, "bad_point"), ("deal", 2, "wrong_threshold"),
                   ("deal", 3, "bad_share"),
                   ("response", 2, "unknown_dealer")}
    FLIGHT.dkg.reset()


def test_board_bad_signature_mints_counter():
    from drand_tpu.dkg.board import BroadcastBoard
    from drand_tpu.utils.logging import default_logger

    pairs, nodes = dkg_scale.make_group(1, prefix="zz-sig")
    board = BroadcastBoard(client=None, own_addr=nodes[0].address(),
                           dealers=nodes, receivers=nodes,
                           nonce=b"zz-sig", logger=default_logger("t"))
    bad = DealBundle(dealer_index=0, commits=(b"x" * 48,),
                     deals=(), session_id=b"zz-sig", signature=b"\x01" * 64)
    before = _reject_count("deal", "bad_signature")
    asyncio.run(board._accept(bad, rebroadcast=False))
    assert _reject_count("deal", "bad_signature") == before + 1
    assert board.deals.qsize() == 0
