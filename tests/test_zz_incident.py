"""Incident engine (ISSUE 15): the chaos-driven detector matrix, the
time-series ring + incident retention bounds, restart persistence,
bundle secret hygiene with real crypto, and the ``?n=`` matrix on the
new debug route (the shared obs.query helper).

Late-alphabet filename per the tier-1 chunking convention (ROADMAP
operational constraint). Host-only: the chaos scenario runs under
structural crypto, the hygiene test's real crypto is share synthesis
only — no device graphs, no fresh XLA compiles.
"""

import json
import os
import urllib.parse

import aiohttp
import pytest
from aiohttp import web
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.http_server.debug import add_trace_routes
from drand_tpu.obs.flight import FlightRecorder
from drand_tpu.obs.health import HealthState
from drand_tpu.obs.incident import (INCIDENTS, IncidentManager, Rule,
                                    default_rules)
from drand_tpu.obs.query import ring_n
from drand_tpu.obs.state import isolated_observability
from drand_tpu.obs.timeseries import TimeSeriesRing
from drand_tpu.testing.chaos import (ChaosBeaconNetwork, FaultEvent,
                                     LinkPolicy, detection_lead,
                                     structural_crypto)

PERIOD = 4


async def _get(port, path):
    async with aiohttp.ClientSession() as s:
        async with s.get(f"http://127.0.0.1:{port}{path}") as r:
            try:
                body = await r.json()
            except Exception:  # noqa: BLE001 — non-JSON error bodies
                body = {}
            return r.status, body


# ---------------------------------------------------------------------------
# 1. the acceptance scenario: the chaos schedule fires every detector,
#    one incident per sustained fault, margin leads missed
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_chaos_detector_matrix(tmp_path):
    """One 8-node schedule drives SIX distinct rules, each minting
    exactly ONE incident inside its fault window: a cross-link delay
    (margin_degraded — rounds before anything misses), then a
    no-quorum partition (missed_round + breaker_open +
    reachability_drop + readiness_flip + sync_stall). The margin
    incident's detection lead matches the PR-11 oracle's, and the
    missed-round bundle fingers the partitioned half from the frozen
    bitmap + reachability. (The two delta-threshold rules —
    ingress_flood/shed_surge — read PROCESS-global counters that every
    in-process node feeds, so their exact-count proof is the unit test
    below; they are excluded here rather than asserted against
    cross-node noise.)"""
    with structural_crypto(), isolated_observability():
        metrics.PEER_BREAKER_STATE.clear()  # stray gauge children from
        # earlier tests would read as pre-existing open breakers
        net = ChaosBeaconNetwork(n=8, t=5, period=PERIOD)
        net.healths[0].note_dkg_complete()  # probe models a real node
        rules = [r for r in default_rules()
                 if r.name not in ("ingress_flood", "shed_surge")]
        mgr = IncidentManager(
            flight=net.flights[0], health=net.healths[0], rules=rules,
            dir_path=str(tmp_path / "incidents"))
        await net.start_all()
        await net.advance_to_genesis()
        heal_round = 13
        sched = [
            FaultEvent(4, "link_all",
                       {"policy": LinkPolicy(delay_s=2.5)}),
            FaultEvent(7, "partition",
                       {"groups": [[0, 1, 2, 3], [4, 5, 6, 7]]}),
            FaultEvent(heal_round, "heal"),
        ]
        obs = await net.run_schedule(
            sched, rounds=16,
            on_round=lambda r, now: mgr.on_round(r, now=now,
                                                 period=PERIOD))
        net.stop_all()

        incs = mgr.incidents()
        by_rule: dict[str, list] = {}
        for inc in incs:
            by_rule.setdefault(inc["rule"], []).append(inc)

        def in_window(rule, lo, hi):
            return [i for i in by_rule.get(rule, [])
                    if i["round"] is not None and lo <= i["round"] <= hi]

        # exactly ONE incident per sustained fault, inside its window
        windows = {"margin_degraded": (4, 6),
                   "missed_round": (8, heal_round),
                   "breaker_open": (7, heal_round),
                   "reachability_drop": (7, heal_round),
                   "readiness_flip": (8, heal_round),
                   "sync_stall": (8, heal_round)}
        for rule, (lo, hi) in windows.items():
            assert len(in_window(rule, lo, hi)) == 1, \
                f"{rule}: {by_rule.get(rule)}"
        # and no rule flapped into a pile of incidents anywhere
        for rule, group in by_rule.items():
            assert len(group) <= 2, f"{rule} minted {len(group)}"

        # margin fired on the delay fault, rounds BEFORE missed —
        # detection lead >= the PR-11 oracle's on the same observations
        margin_round = in_window("margin_degraded", 4, 6)[0]["round"]
        missed_inc = in_window("missed_round", 8, heal_round)[0]
        assert margin_round == 4
        assert missed_inc["round"] > margin_round
        oracle = detection_lead(obs, PERIOD)
        assert oracle["lead_rounds"] is not None
        assert missed_inc["round"] - margin_round >= oracle["lead_rounds"]

        # the missed-round bundle froze the partition evidence: the
        # other half is named missing by the bitmap AND unreachable
        bundle = mgr.get_bundle(missed_inc["id"])
        assert bundle is not None
        sus = bundle["suspect_peers"]
        assert sus["missing"] == [4, 5, 6, 7]
        assert sus["unreachable"] == [4, 5, 6, 7]
        assert sus["invalid"] == []
        # the frozen flight slice carries the '####....' bitmaps
        part_bitmaps = [r["bitmap"] for r in bundle["flight"]["rounds"]
                        if r["round"] >= 7 and r["bitmap"]]
        assert part_bitmaps
        assert all(bm[4:] == "...." for bm in part_bitmaps)
        # evidence inventory: ts window, health, config all frozen
        assert bundle["timeseries"]
        assert bundle["health"]["missed_total"] >= 1
        assert bundle["config"]["fingerprint"]
        # sustained faults re-fired into the OPEN incident, not new ones
        assert missed_inc["fired"] >= 2
        # the catalogue counters moved once per mint
        assert _sample_count(metrics.GROUP_REGISTRY, "incidents",
                             rule="missed_round",
                             severity="critical") >= 1


def test_flood_and_shed_delta_rules():
    """The two delta-threshold rules against their own counters: a
    reject surge >= FLOOD_MIN in one sample mints ingress_flood, a
    shed surge >= SHED_MIN mints shed_surge; sub-threshold deltas mint
    nothing (counters are global — deltas, not levels, trigger)."""
    flight, health = FlightRecorder(), HealthState()
    mgr = IncidentManager(flight=flight, health=health)
    genesis = 1_000_000

    def rejects(rnd, count):
        # the REAL ingress-reject path: invalid partials through the
        # recorder feed beacon_ingress_rejects_total
        for _ in range(count):
            flight.note_partial(rnd, index=0, source="grpc",
                                verdict="invalid", now=float(genesis),
                                period=PERIOD, genesis=genesis, n=3,
                                threshold=2)

    mgr.on_round(1, now=1.0, period=PERIOD)  # delta baseline
    # below both thresholds: quiet
    rejects(2, 3)
    metrics.RELAY_SHED.labels(reason="watcher_cap").inc(2)
    mgr.on_round(2, now=5.0, period=PERIOD)
    assert mgr.incidents() == []
    # a flood and a shed storm in the next sample window
    rejects(2, 40)
    metrics.RELAY_SHED.labels(reason="watcher_cap").inc(20)
    mgr.on_round(3, now=9.0, period=PERIOD)
    rules = sorted(i["rule"] for i in mgr.incidents())
    assert rules == ["ingress_flood", "shed_surge"]


# ---------------------------------------------------------------------------
# 2. cooldown + dedup: one sustained fault = one incident; a fresh
#    fault after the cooldown mints a second
# ---------------------------------------------------------------------------

def test_sustained_fault_dedup_and_cooldown():
    flight, health = FlightRecorder(), HealthState()
    mgr = IncidentManager(flight=flight, health=health)
    genesis, period = 1_000_000, 4

    def advance(r, stored):
        b = genesis + (r - 1) * period
        if stored:
            health.note_round_stored(r, 0.2, period)
            health.observe_chain(b + 0.5, period, genesis, r)
        else:
            health.observe_chain(b + 3.9, period, genesis)
        mgr.on_round(r, now=b + 0.5, period=period)

    for r in range(1, 4):
        advance(r, stored=True)
    assert mgr.incidents() == []
    # sustained fault: rounds 4-8 all miss — ONE incident, re-fired
    for r in range(4, 9):
        advance(r, stored=False)
    incs = [i for i in mgr.incidents() if i["rule"] == "missed_round"]
    assert len(incs) == 1
    assert incs[0]["state"] == "open"
    assert incs[0]["fired"] >= 3
    # recovery: stores resume, incident closes after clear_after quiet
    # samples... but a re-miss INSIDE the cooldown must NOT re-mint
    # (a miss counts once the NEXT round's probe sees the full period
    # gone — two unstored rounds make the first one count)
    for r in range(9, 12):
        advance(r, stored=True)
    incs = [i for i in mgr.incidents() if i["rule"] == "missed_round"]
    assert incs[0]["state"] == "closed"
    advance(12, stored=False)
    advance(13, stored=False)  # round 12's miss counts here, ~8s after
    assert len([i for i in mgr.incidents()  # close: inside the 30s
                if i["rule"] == "missed_round"]) == 1  # cooldown
    # past the cooldown a NEW fault is a NEW incident
    for r in range(14, 22):
        advance(r, stored=True)
    advance(22, stored=False)
    advance(23, stored=False)  # round 22's miss, ~40s past the close
    assert len([i for i in mgr.incidents()
                if i["rule"] == "missed_round"]) == 2


# ---------------------------------------------------------------------------
# 3. bounds: ts ring, spool rotation, incident-dir rotation
# ---------------------------------------------------------------------------

def test_timeseries_ring_and_spool_bounds(tmp_path):
    spool = str(tmp_path / "ts.ndjson")
    ring = TimeSeriesRing(max_samples=8, spool_path=spool,
                          max_spool_bytes=2048)
    for i in range(64):
        ring.append({"t": float(i), "round": i, "missed_total": i,
                     "ingress_rejects": 0.0, "watcher_shed": 0.0})
    ring.flush()
    assert len(ring) == 8
    assert [s["round"] for s in ring.window()] == list(range(56, 64))
    # deltas are counter-aware
    assert ring.window()[-1]["deltas"]["missed_total"] == 1.0
    # disk bounded at ~2x the cap by the OTLP rotation pattern
    assert os.path.getsize(spool) <= 2048
    assert os.path.getsize(spool + ".1") <= 2048


def test_open_incident_survives_rotation(tmp_path):
    """An incident held open across many newer mints is never evicted
    (memory or disk) while open — /debug/incidents stays consistent
    with the active count and the eventual close lands on disk."""
    flight, health = FlightRecorder(), HealthState()
    sticky = Rule("custom", "warning", "edge",
                  lambda w, ctx: ("on" if w[-1]["round"] < 90
                                  else None),
                  cooldown_s=0.0, clear_after=1)
    churn = Rule("shed_surge", "warning", "edge",
                 lambda w, ctx: ("even" if w[-1]["round"] % 2 == 0
                                 and w[-1]["round"] < 50 else None),
                 cooldown_s=0.0, clear_after=1)
    mgr = IncidentManager(flight=flight, health=health,
                          rules=[sticky, churn],
                          dir_path=str(tmp_path / "inc"),
                          max_incidents=3)
    for r in range(1, 14):  # ends on an odd round: churn all closed
        mgr.on_round(r, now=float(r), period=4)
    # the sticky incident (minted FIRST) is still listed and open
    # despite 6 younger churn incidents through a bound of 3
    open_incs = [i for i in mgr.incidents(100) if i["state"] == "open"]
    assert [i["id"] for i in open_incs] == ["inc-00001-custom"]
    assert mgr.active_count() == 1
    assert "inc-00001-custom.json" in os.listdir(tmp_path / "inc")
    # close it: the close state reaches the still-present file
    for r in range(90, 93):
        mgr.on_round(r, now=float(r), period=4)
    disk = json.load(open(tmp_path / "inc" / "inc-00001-custom.json"))
    assert disk["state"] == "closed"


def test_readiness_flip_immune_to_restored_history(tmp_path):
    """Spool-restored pre-restart samples (ready=True) must not arm
    the readiness-flip rule: a restart straight into catch-up lag is
    not a live flip."""
    spool = str(tmp_path / "ts.ndjson")
    flight, health = FlightRecorder(), HealthState()
    health.note_dkg_complete()
    genesis, period = 1_000_000, 4
    mgr = IncidentManager(flight=flight, health=health)
    mgr.configure(spool_path=spool)
    for r in range(1, 4):  # healthy, ready samples -> spool
        b = genesis + (r - 1) * period
        health.note_round_stored(r, 0.2, period)
        health.observe_chain(b + 0.5, period, genesis, r)
        mgr.on_round(r, now=b + 0.5, period=period)
    assert mgr.ring.window()[-1]["ready"]
    mgr.ring.flush()  # healthy samples buffer (mints force-flush);
    # a graceful handover flushes — a SIGKILL may lose <=FLUSH_EVERY

    # "restart": fresh manager+health, spool restored, node lagging
    flight2, health2 = FlightRecorder(), HealthState()
    health2.note_dkg_complete()
    mgr2 = IncidentManager(flight=flight2, health=health2)
    mgr2.configure(spool_path=spool)
    assert len(mgr2.ring) == 3
    b = genesis + 9 * period  # 10 rounds later, head far behind
    health2.observe_chain(b, period, genesis, 3)
    mgr2.on_round(10, now=b, period=period)
    assert not any(i["rule"] == "readiness_flip"
                   for i in mgr2.incidents()), mgr2.incidents()
    # but a LIVE flip still fires: become ready, then lag again
    for r in range(11, 14):
        bb = genesis + (r - 1) * period
        health2.note_round_stored(r, 0.2, period)
        health2.observe_chain(bb + 0.5, period, genesis, r)
        mgr2.on_round(r, now=bb + 0.5, period=period)
    bb = genesis + 19 * period
    health2.observe_chain(bb, period, genesis)
    mgr2.on_round(20, now=bb, period=period)
    assert any(i["rule"] == "readiness_flip" for i in mgr2.incidents())


def test_memory_only_bundle_tracks_lifecycle():
    """On a node with NO incident dir (relay default), the bundle
    served by get_bundle must carry the same lifecycle the listing
    shows — not the state frozen at mint."""
    flight, health = FlightRecorder(), HealthState()
    toggle = Rule("custom", "warning", "edge",
                  lambda w, ctx: ("on" if w[-1]["round"] < 4 else None),
                  cooldown_s=0.0, clear_after=1)
    mgr = IncidentManager(flight=flight, health=health, rules=[toggle])
    for r in range(1, 6):
        mgr.on_round(r, now=float(r), period=4)
    [inc] = mgr.incidents()
    assert inc["state"] == "closed"
    bundle = mgr.get_bundle(inc["id"])
    assert bundle["state"] == "closed"
    assert bundle["closed_at"] == inc["closed_at"]
    assert bundle["fired"] == inc["fired"] >= 3


def test_readiness_incident_latches_through_long_outage():
    """Once open, the readiness incident stays open for the whole
    outage even after every live 'ready' sample ages out of the
    (small, here) window — it closes only when ready returns."""
    flight, health = FlightRecorder(), HealthState()
    health.note_dkg_complete()
    genesis, period = 1_000_000, 4
    mgr = IncidentManager(flight=flight, health=health,
                          ring=TimeSeriesRing(max_samples=6))
    for r in range(1, 4):  # ready baseline
        b = genesis + (r - 1) * period
        health.note_round_stored(r, 0.2, period)
        health.observe_chain(b + 0.5, period, genesis, r)
        mgr.on_round(r, now=b + 0.5, period=period)
    # a LONG outage: 20 not-ready samples, 3x the window size
    for r in range(4, 24):
        b = genesis + (r - 1) * period
        health.observe_chain(b + 3.9, period, genesis)
        mgr.on_round(r, now=b + 3.9, period=period)
    flips = [i for i in mgr.incidents(100)
             if i["rule"] == "readiness_flip"]
    assert len(flips) == 1
    assert flips[0]["state"] == "open", flips
    # recovery closes it
    for r in range(24, 28):
        b = genesis + (r - 1) * period
        health.note_round_stored(r, 0.2, period)
        health.observe_chain(b + 0.5, period, genesis, r)
        mgr.on_round(r, now=b + 0.5, period=period)
    flips = [i for i in mgr.incidents(100)
             if i["rule"] == "readiness_flip"]
    assert len(flips) == 1 and flips[0]["state"] == "closed"


def test_incident_dir_rotation_bound(tmp_path):
    flight, health = FlightRecorder(), HealthState()
    # a toggling rule: fires on even rounds, clears on odd, no cooldown
    toggle = Rule("custom", "warning", "edge",
                  lambda w, ctx: ("even" if w[-1]["round"] % 2 == 0
                                  else None),
                  cooldown_s=0.0, clear_after=1)
    mgr = IncidentManager(flight=flight, health=health, rules=[toggle],
                          dir_path=str(tmp_path / "inc"),
                          max_incidents=3)
    for r in range(1, 13):
        mgr.on_round(r, now=float(r), period=4)
    # 6 mint/close cycles -> memory AND disk both bounded at 3
    assert len(mgr.incidents(100)) == 3
    files = sorted(os.listdir(tmp_path / "inc"))
    assert len(files) == 3
    # oldest were rotated away, newest kept (ids are seq-ordered)
    assert files[-1].startswith("inc-00006-")


# ---------------------------------------------------------------------------
# 4. restart persistence: spool + incident dir reload
# ---------------------------------------------------------------------------

def test_restart_persistence(tmp_path):
    flight, health = FlightRecorder(), HealthState()
    d, spool = str(tmp_path / "inc"), str(tmp_path / "ts.ndjson")
    genesis, period = 1_000_000, 4
    mgr = IncidentManager(flight=flight, health=health, dir_path=d)
    mgr.configure(spool_path=spool)
    for r in range(1, 4):
        b = genesis + (r - 1) * period
        health.note_round_stored(r, 0.2, period)
        health.observe_chain(b + 0.5, period, genesis, r)
        mgr.on_round(r, now=b + 0.5, period=period)
    # miss rounds 4-5 -> one persisted incident
    for r in range(4, 6):
        b = genesis + (r - 1) * period
        health.observe_chain(b + 3.9, period, genesis)
        mgr.on_round(r, now=b + 3.9, period=period)
    ids = [i["id"] for i in mgr.incidents()]
    assert len(ids) == 1

    # "restart": a fresh manager over the same disk state
    mgr2 = IncidentManager(flight=flight, health=health)
    mgr2.configure(dir_path=d, spool_path=spool)
    assert [i["id"] for i in mgr2.incidents()] == ids
    # the bundle is served from disk (memory holds the summary only)
    bundle = mgr2.get_bundle(ids[0])
    assert bundle is not None and bundle["rule"] == "missed_round"
    # the ring restored the spooled history, oldest intact
    assert len(mgr2.ring) == 5
    assert mgr2.ring.window()[0]["round"] == 1
    # the seq counter resumed past the loaded ids: no collision
    mgr2._lock.acquire()
    try:
        assert mgr2._seq >= 1
    finally:
        mgr2._lock.release()
    # path traversal never reaches the filesystem
    assert mgr2.get_bundle("../../etc/passwd") is None


# ---------------------------------------------------------------------------
# 5. bundle secret hygiene with real crypto
# ---------------------------------------------------------------------------

def test_bundle_secret_hygiene_real_crypto(monkeypatch):
    """A bundle captured in a process holding REAL shares (and a
    secret-looking env knob) contains no share value in decimal or
    hex, and the config fingerprint redacted the env secret."""
    from drand_tpu.testing.harness import make_test_group

    monkeypatch.setenv("DRAND_TPU_SETUP_SECRET", "hunter2-do-not-leak")
    _group, _pairs, shares = make_test_group(3, 2, PERIOD, 1_000_000,
                                             seed=b"incident-hygiene")
    flight, health = FlightRecorder(), HealthState()
    mgr = IncidentManager(flight=flight, health=health)
    for r in range(1, 4):
        b = 1_000_000 + (r - 1) * PERIOD
        for idx in range(2):
            flight.note_partial(r, index=idx, source="grpc",
                                verdict="valid", now=b + 0.2,
                                period=PERIOD, genesis=1_000_000,
                                n=3, threshold=2)
        health.note_round_stored(r, 0.2, PERIOD)
        health.observe_chain(b + 0.5, PERIOD, 1_000_000, r)
        mgr.on_round(r, now=b + 0.5, period=PERIOD)
    blob = json.dumps(mgr.capture_bundle())
    assert "pri_share" not in blob
    for s in shares:
        assert str(s.pri_share.value) not in blob
        assert format(s.pri_share.value, "x") not in blob
    assert "hunter2-do-not-leak" not in blob
    assert "<redacted>" in blob


# ---------------------------------------------------------------------------
# 6. the ?n= matrix on /debug/incidents + the shared helper + routes
# ---------------------------------------------------------------------------

def test_ring_n_shared_helper_semantics():
    """The one validator behind all three ?n= routes: plain base-10
    only, clamp to [1, cap], None for absent -> default."""
    assert ring_n(None, default=8, cap=128) == 8
    assert ring_n("5", default=8, cap=128) == 5
    assert ring_n("-3", default=8, cap=128) == 1
    assert ring_n("0", default=8, cap=128) == 1
    assert ring_n("999999", default=8, cap=128) == 128
    assert ring_n("+7", default=8, cap=128) == 7
    assert ring_n(" 12 ", default=8, cap=128) == 12
    for bad in ("", "zzz", "1.5", "1e3", "0x10", "1_0", "١٢", "+-5"):
        assert ring_n(bad, default=8, cap=128) is None, bad
    # the query-string gotcha the tests percent-encode around: a
    # literal '+' in a URL decodes to a space mid-token -> invalid
    assert urllib.parse.unquote_plus("1+1") == "1 1"
    assert ring_n("1 1", default=8, cap=128) is None


@pytest.mark.asyncio
async def test_incident_routes_and_n_matrix(tmp_path):
    """/debug/incidents serves the singleton's summaries with the same
    hardened ?n= contract as the trace/flight routes; {id} serves the
    frozen bundle; /debug/support-bundle runs the manual capture."""
    with isolated_observability():
        from drand_tpu.obs.health import HEALTH

        genesis, period = 1_000_000, 4
        HEALTH.note_dkg_complete()
        for r in range(1, 3):
            b = genesis + (r - 1) * period
            HEALTH.note_round_stored(r, 0.2, period)
            HEALTH.observe_chain(b + 0.5, period, genesis, r)
            INCIDENTS.on_round(r, now=b + 0.5, period=period)
        for r in range(3, 5):  # missed -> one incident on the singleton
            b = genesis + (r - 1) * period
            HEALTH.observe_chain(b + 3.9, period, genesis)
            INCIDENTS.on_round(r, now=b + 3.9, period=period)
        assert len(INCIDENTS.incidents()) >= 1

        app = web.Application()
        add_trace_routes(app)
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = site._server.sockets[0].getsockname()[1]
        try:
            # the URL-encoding matrix (mirrors the trace-route matrix:
            # '+' decodes to space, so explicit signs percent-encode)
            for q, want in (("zzz", 400), ("1.5", 400), ("1e3", 400),
                            ("0x10", 400), ("", 400), ("%2B-5", 400),
                            ("-5", 200), ("0", 200), ("999999999", 200),
                            ("%2B7", 200), ("8", 200)):
                status, body = await _get(port,
                                          f"/debug/incidents?n={q}")
                assert status == want, f"n={q!r} -> {status}"
                if want == 200:
                    assert "incidents" in body and "active" in body
            status, body = await _get(port, "/debug/incidents")
            assert status == 200
            inc = body["incidents"][0]
            assert inc["rule"] == "missed_round"
            # the bundle route serves the frozen evidence by id
            status, bundle = await _get(port,
                                        f"/debug/incidents/{inc['id']}")
            assert status == 200
            assert bundle["id"] == inc["id"]
            assert "timeseries" in bundle and "flight" in bundle \
                and "config" in bundle
            status, _ = await _get(port, "/debug/incidents/inc-99999-nope")
            assert status == 404
            # manual capture: the bundle writer verbatim, no new incident
            n_before = len(INCIDENTS.incidents())
            status, sup = await _get(port, "/debug/support-bundle")
            assert status == 200
            assert sup["rule"] == "manual" and sup["state"] == "manual"
            assert "timeseries" in sup and "health" in sup
            assert len(INCIDENTS.incidents()) == n_before
        finally:
            await runner.cleanup()


# ---------------------------------------------------------------------------
# 7. the healthz pull model drives detection with zero stores
# ---------------------------------------------------------------------------

def test_poll_pull_model_and_rate_limit():
    """A fully stalled chain stores nothing — probe-driven poll()
    samples must still fire the missed-round rule; and a probe storm
    (many polls inside the min interval) must not grow the ring."""
    flight, health = FlightRecorder(), HealthState()
    mgr = IncidentManager(flight=flight, health=health)
    genesis, period = 1_000_000, 4
    # one stored round seeds head + period context
    health.note_round_stored(1, 0.2, period)
    health.observe_chain(genesis + 0.5, period, genesis, 1)
    mgr.on_round(1, now=genesis + 0.5, period=period)
    # then the chain dies: only probes observe, 5 rounds pass
    for r in range(2, 7):
        b = genesis + (r - 1) * period
        health.observe_chain(b + 3.9, period, genesis)
        assert mgr.poll(b + 3.9) is not None
        for _ in range(10):  # probe storm inside the min interval
            assert mgr.poll(b + 3.95) is None
    # the stalled chain fires BOTH pull-model rules (sync_stall rides
    # the same lag threshold), each exactly once
    assert sorted(i["rule"] for i in mgr.incidents()) == \
        ["missed_round", "sync_stall"]
    assert len(mgr.ring) == 6
