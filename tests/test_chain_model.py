"""Chain-model tests: time math, beacon messages, stores.
Mirrors reference chain/time_test.go, chain/beacon.go semantics."""

import os
import tempfile

import pytest

from drand_tpu.chain import time_math
from drand_tpu.chain.beacon import (
    Beacon,
    message,
    message_v2,
    randomness_from_signature,
    round_to_bytes,
)
from drand_tpu.chain.store import (
    AppendStore,
    CallbackStore,
    MemStore,
    SQLiteStore,
    StoreError,
    genesis_beacon,
)
from drand_tpu.chain.info import Info
from drand_tpu.crypto.curves import PointG1


class TestTimeMath:
    PERIOD, GENESIS = 30, 1_700_000_000

    def test_round_zero_is_genesis(self):
        assert time_math.time_of_round(self.PERIOD, self.GENESIS, 0) == self.GENESIS

    def test_round_one_at_genesis(self):
        assert time_math.time_of_round(self.PERIOD, self.GENESIS, 1) == self.GENESIS

    def test_round_k(self):
        assert (
            time_math.time_of_round(self.PERIOD, self.GENESIS, 10)
            == self.GENESIS + 9 * self.PERIOD
        )

    def test_next_round_before_genesis(self):
        r, t = time_math.next_round(self.GENESIS - 100, self.PERIOD, self.GENESIS)
        assert (r, t) == (1, self.GENESIS)

    def test_next_round_progression(self):
        # right at genesis, round 1 is current; next is 2
        r, t = time_math.next_round(self.GENESIS, self.PERIOD, self.GENESIS)
        assert r == 2 and t == self.GENESIS + self.PERIOD
        assert time_math.current_round(self.GENESIS, self.PERIOD, self.GENESIS) == 1
        mid = self.GENESIS + self.PERIOD + 3
        assert time_math.current_round(mid, self.PERIOD, self.GENESIS) == 2

    def test_time_round_inverse(self):
        for k in (1, 2, 77, 10_000):
            t = time_math.time_of_round(self.PERIOD, self.GENESIS, k)
            assert time_math.current_round(t, self.PERIOD, self.GENESIS) == k

    def test_overflow_guard(self):
        assert (
            time_math.time_of_round(self.PERIOD, self.GENESIS, 1 << 62)
            == time_math.TIME_OF_ROUND_ERROR_VALUE
        )


class TestBeaconModel:
    def test_message_derivation(self):
        prev = b"\xaa" * 96
        assert message(5, prev) != message(6, prev)
        assert message(5, prev) != message(5, b"\xbb" * 96)
        assert message_v2(5) == message_v2(5)
        assert message_v2(5) != message_v2(6)
        # V1 message binds the previous signature; V2 does not
        assert message(5, prev) != message_v2(5)
        assert round_to_bytes(1) == b"\x00" * 7 + b"\x01"

    def test_randomness_is_sha256_of_sig(self):
        import hashlib

        sig = b"\x01" * 96
        b = Beacon(round=1, previous_sig=b"", signature=sig)
        assert b.randomness() == hashlib.sha256(sig).digest()
        assert randomness_from_signature(sig) == b.randomness()

    def test_marshal_roundtrip(self):
        b = Beacon(round=7, previous_sig=b"\x01" * 96, signature=b"\x02" * 96,
                   signature_v2=b"\x03" * 96)
        assert Beacon.unmarshal(b.marshal()).equal(b)
        b2 = Beacon(round=7, previous_sig=b"\x01" * 96, signature=b"\x02" * 96)
        assert not b2.is_v2()
        assert Beacon.unmarshal(b2.marshal()).equal(b2)


def _mk_chain(k: int) -> list[Beacon]:
    out = [Beacon(round=0, previous_sig=b"", signature=b"genesis")]
    for i in range(1, k + 1):
        out.append(
            Beacon(round=i, previous_sig=out[-1].signature,
                   signature=b"sig%d" % i)
        )
    return out


class TestStores:
    @pytest.mark.parametrize("backend", ["mem", "sqlite"])
    def test_put_get_last_cursor(self, backend, tmp_path):
        store = MemStore() if backend == "mem" else SQLiteStore(str(tmp_path / "c.db"))
        chain = _mk_chain(5)
        for b in chain:
            store.put(b)
        assert len(store) == 6
        assert store.last().round == 5
        assert store.get(3).signature == b"sig3"
        assert store.get(99) is None
        assert [b.round for b in store.cursor()] == list(range(6))
        assert [b.round for b in store.cursor_from(3)] == [3, 4, 5]
        store.del_round(5)
        assert store.last().round == 4
        store.close()

    def test_sqlite_persistence(self, tmp_path):
        path = str(tmp_path / "chain.db")
        s1 = SQLiteStore(path)
        for b in _mk_chain(3):
            s1.put(b)
        s1.close()
        s2 = SQLiteStore(path)
        assert s2.last().round == 3
        assert s2.get(2).previous_sig == b"sig1"
        s2.close()

    def test_append_store_monotonicity(self):
        inner = MemStore()
        chain = _mk_chain(3)
        inner.put(chain[0])
        store = AppendStore(inner)
        store.put(chain[1])
        store.put(chain[2])
        # skipping a round fails
        with pytest.raises(StoreError):
            store.put(Beacon(round=5, previous_sig=chain[2].signature, signature=b"x"))
        # wrong previous signature fails
        with pytest.raises(StoreError):
            store.put(Beacon(round=3, previous_sig=b"wrong", signature=b"x"))
        store.put(chain[3])
        assert store.last().round == 3

    def test_callback_store(self):
        inner = MemStore()
        chain = _mk_chain(2)
        store = CallbackStore(inner)
        seen = []
        store.add_callback("t", lambda b: seen.append(b.round))
        for b in chain:
            store.put(b)
        assert seen == [1, 2]  # genesis (round 0) never triggers callbacks
        store.remove_callback("t")
        store.put(Beacon(round=3, previous_sig=chain[-1].signature, signature=b"s3"))
        assert seen == [1, 2]

    def test_genesis_beacon(self):
        info = Info(
            public_key=PointG1.generator(),
            period=30,
            genesis_time=1000,
            genesis_seed=b"\x42" * 32,
        )
        g = genesis_beacon(info)
        assert g.round == 0 and g.signature == b"\x42" * 32
        # info JSON codec
        rt = Info.from_json(info.to_json())
        assert rt.equal(info) and rt.hash() == info.hash()
