"""Golden tests: batch-last field/tower arithmetic (ops/bl.py) vs the host
reference (crypto/fields.py), both as plain jnp math and inside a real
Pallas kernel (interpret mode on CPU; the TPU path is exercised by the
engine's known-answer validation and bench.py)."""

import random

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytestmark = pytest.mark.device

from drand_tpu.crypto import fields as hf
from drand_tpu.crypto.fields import P
from drand_tpu.ops import bl

B = 8  # batch lanes under test (kernels use 128; math is lane-agnostic)
rng = random.Random(0xB117)


def rand_fp_ints(n=B):
    return [rng.randrange(P) for _ in range(n)]


def rand_f2():
    return [hf.Fp2(rng.randrange(P), rng.randrange(P)) for _ in range(B)]


def rand_f6():
    return [hf.Fp6(*(rand_f2()[0] for _ in range(3))) for _ in range(B)]


def rand_f12():
    return [hf.Fp12(rand_f6()[0], rand_f6()[0]) for _ in range(B)]


# -- packing helpers --------------------------------------------------------

def pack_f2(xs):
    return np.stack([bl.pack_fp([x.c0 for x in xs]),
                     bl.pack_fp([x.c1 for x in xs])], axis=0)


def unpack_f2(a):
    c0 = bl.unpack_fp(np.asarray(a)[0])
    c1 = bl.unpack_fp(np.asarray(a)[1])
    return [hf.Fp2(x, y) for x, y in zip(c0, c1)]


def pack_f6(xs):
    return np.stack([pack_f2([x.c0 for x in xs]),
                     pack_f2([x.c1 for x in xs]),
                     pack_f2([x.c2 for x in xs])], axis=0)


def unpack_f6(a):
    a = np.asarray(a)
    return [hf.Fp6(x, y, z) for x, y, z in zip(
        unpack_f2(a[0]), unpack_f2(a[1]), unpack_f2(a[2]))]


def pack_f12(xs):
    return np.stack([pack_f6([x.c0 for x in xs]),
                     pack_f6([x.c1 for x in xs])], axis=0)


def unpack_f12(a):
    a = np.asarray(a)
    return [hf.Fp12(x, y) for x, y in zip(unpack_f6(a[0]), unpack_f6(a[1]))]


# -- Fp ---------------------------------------------------------------------

def test_mont_mul_add_sub_neg_golden():
    xs, ys = rand_fp_ints(), rand_fp_ints()
    a, b = jnp.asarray(bl.pack_fp(xs)), jnp.asarray(bl.pack_fp(ys))
    assert bl.unpack_fp(bl.mont_mul(a, b)) == [x * y % P
                                               for x, y in zip(xs, ys)]
    assert bl.unpack_fp(bl.add(a, b)) == [(x + y) % P
                                          for x, y in zip(xs, ys)]
    assert bl.unpack_fp(bl.sub(a, b)) == [(x - y) % P
                                          for x, y in zip(xs, ys)]
    assert bl.unpack_fp(bl.neg(b)) == [(-y) % P for y in ys]
    assert bl.unpack_fp(bl.mul_small(a, 9)) == [x * 9 % P for x in xs]


def test_conv_modes_agree():
    xs, ys = rand_fp_ints(), rand_fp_ints()
    a, b = jnp.asarray(bl.pack_fp(xs)), jnp.asarray(bl.pack_fp(ys))
    prev = bl.CONV_MODE
    outs = {}
    try:
        for mode in ("unroll", "loop", "tree"):
            bl.CONV_MODE = mode
            outs[mode] = bl.unpack_fp(np.asarray(bl.mont_mul(a, b)))
    finally:
        bl.CONV_MODE = prev
    assert outs["unroll"] == outs["loop"] == outs["tree"]


def test_conv_tree_bit_identical_raw():
    # tree and karatsuba forms must be pure reassociations: identical
    # RAW limb coefficients (not just values) to the windowed schoolbook
    # form, for both the 64-limb product and the 32-limb low-half conv,
    # incl. worst-case lazy-carry magnitudes (limbs up to 2^13)
    rng = np.random.default_rng(7)
    for hi in (1 << 12, 1 << 13):
        a = jnp.asarray(rng.integers(0, hi, (bl.NLIMBS, 4), dtype=np.int32))
        b = jnp.asarray(rng.integers(0, hi, (bl.NLIMBS, 4), dtype=np.int32))
        for out_len in (2 * bl.NLIMBS, bl.NLIMBS):
            ref = np.asarray(bl._conv_unrolled(a, b, out_len))
            np.testing.assert_array_equal(
                np.asarray(bl._conv_tree(a, b, out_len)), ref)
            np.testing.assert_array_equal(
                np.asarray(bl._conv_karatsuba(a, b, out_len)), ref)


def test_fp_inv_golden():
    xs = rand_fp_ints()
    a = jnp.asarray(bl.pack_fp(xs))
    assert bl.unpack_fp(bl.fp_inv(a)) == [pow(x, P - 2, P) for x in xs]


# -- Fp2 / Fp6 / Fp12 -------------------------------------------------------

def test_f2_ops_golden():
    xs, ys = rand_f2(), rand_f2()
    a, b = jnp.asarray(pack_f2(xs)), jnp.asarray(pack_f2(ys))
    assert unpack_f2(bl.f2_mul(a, b)) == [x * y for x, y in zip(xs, ys)]
    assert unpack_f2(bl.f2_sqr(a)) == [x * x for x in xs]
    assert unpack_f2(bl.f2_mul_by_xi(a)) == [x * hf.XI for x in xs]
    assert unpack_f2(bl.f2_inv(a)) == [x.inverse() for x in xs]
    assert unpack_f2(bl.f2_conj(a)) == [x.conjugate() for x in xs]


def test_f6_f12_ops_golden():
    x6, y6 = rand_f6(), rand_f6()
    a6, b6 = jnp.asarray(pack_f6(x6)), jnp.asarray(pack_f6(y6))
    assert unpack_f6(bl.f6_mul(a6, b6)) == [x * y for x, y in zip(x6, y6)]
    assert unpack_f6(bl.f6_inv(a6)) == [x.inverse() for x in x6]
    x12, y12 = rand_f12(), rand_f12()
    a12, b12 = jnp.asarray(pack_f12(x12)), jnp.asarray(pack_f12(y12))
    assert unpack_f12(bl.f12_mul(a12, b12)) == [x * y
                                                for x, y in zip(x12, y12)]
    assert unpack_f12(bl.f12_sqr(a12)) == [x * x for x in x12]
    assert unpack_f12(bl.f12_conj(a12)) == [x.conjugate() for x in x12]
    assert unpack_f12(bl.f12_inv(a12)) == [x.inverse() for x in x12]
    for k in (1, 2, 3):
        assert unpack_f12(bl.f12_frobenius(a12, k)) == \
            [x.frobenius(k) for x in x12]


def test_cyclotomic_sqr_golden():
    # cyclotomic elements: m^((p^6-1)(p^2+1)) for random m
    xs = []
    for x in rand_f12()[:3]:
        e = x.frobenius(3).frobenius(3) * x.inverse()  # x^(p^6-1)
        xs.append(e.frobenius(2) * e)                  # ^(p^2+1)
    a = jnp.asarray(pack_f12(xs))
    assert unpack_f12(bl.f12_cyclotomic_sqr(a)) == \
        [x.cyclotomic_square() for x in xs]


# -- inside a real Pallas kernel (interpret mode) ---------------------------

def test_f2_mul_inside_pallas_kernel_interpret():
    from jax.experimental import pallas as pl

    xs, ys = rand_f2(), rand_f2()
    a, b = jnp.asarray(pack_f2(xs)), jnp.asarray(pack_f2(ys))

    def kernel(c_ref, a_ref, b_ref, o_ref):
        with bl.const_context(c_ref[:]):
            o_ref[:] = bl.f2_mul(a_ref[:], b_ref[:])

    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(a.shape, a.dtype),
        interpret=True,
    )(jnp.asarray(bl.CONST_BUFFER), a, b)
    assert unpack_f2(out) == [x * y for x, y in zip(xs, ys)]


def test_exact_zero_tests():
    xs = rand_fp_ints()
    a = jnp.asarray(bl.pack_fp(xs))
    assert not np.asarray(bl.is_zero_mod_p(a)).any()
    # a - a is a non-canonical representation of 0 (mod p)
    z = bl.sub(a, a)
    assert np.asarray(bl.is_zero_mod_p(z)).all()
    # a + (-a) likewise
    z2 = bl.add(a, bl.neg(a))
    assert np.asarray(bl.is_zero_mod_p(z2)).all()


def test_f12_is_one():
    one = bl.f12_one((), B)
    assert np.asarray(bl.f12_is_one(one)).all()
    xs = rand_f12()
    a = jnp.asarray(pack_f12(xs))
    assert not np.asarray(bl.f12_is_one(a)).any()
    # one * x * x^-1 == one exercises the full mul/inv pipeline
    prod = bl.f12_mul(a, bl.f12_inv(a))
    assert np.asarray(bl.f12_is_one(prod)).all()
