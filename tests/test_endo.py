"""ψ endomorphism fast paths (crypto/endo.py): Scott subgroup check and
Budroni-Pintore cofactor clearing vs the generic scalar oracles."""

import random

from drand_tpu.crypto import endo
from drand_tpu.crypto import hash_to_curve as h2c
from drand_tpu.crypto.curves import PointG2
from drand_tpu.crypto.fields import R
from drand_tpu.crypto.hash_to_curve import _H_CLEAR

rng = random.Random(0xE2D0)


def _pre_clearing_point(tag: bytes) -> PointG2:
    """A curve point NOT (generically) in the r-order subgroup."""
    u0, u1 = h2c.hash_to_field_fp2(tag, h2c.DEFAULT_DST_G2, 2)
    return h2c.map_to_curve_g2(u0) + h2c.map_to_curve_g2(u1)


def test_psi_eigenvalue_on_subgroup():
    from drand_tpu.crypto.fields import X_BLS

    for _ in range(3):
        g = PointG2.generator().mul(rng.randrange(1, R))
        assert endo.psi(g) == endo._mul_int(g, X_BLS)
        assert endo.psi2(g) == endo.psi(endo.psi(g))


def test_subgroup_check_accepts_and_rejects():
    for _ in range(3):
        g = PointG2.generator().mul(rng.randrange(1, R))
        assert endo.subgroup_check_fast(g)
        assert g.in_subgroup()  # oracle agrees
    for i in range(3):
        q = _pre_clearing_point(b"reject-%d" % i)
        assert endo.subgroup_check_fast(q) == q.in_subgroup()
        # a random map output is (overwhelmingly) outside the subgroup
        assert not endo.subgroup_check_fast(q)


def test_bp_clearing_equals_generic():
    for i in range(3):
        q = _pre_clearing_point(b"clear-%d" % i)
        assert endo.clear_cofactor_fast(q) == q.mul(_H_CLEAR)
    # and the cleared point is in the subgroup
    assert endo.clear_cofactor_fast(
        _pre_clearing_point(b"clear-final")).in_subgroup()


def test_psi3_is_psi_cubed():
    for _ in range(2):
        g = PointG2.generator().mul(rng.randrange(1, R))
        assert endo.psi3(g) == endo.psi(endo.psi(endo.psi(g)))
        assert endo.psi3(g) == endo.psi(endo.psi2(g))


def test_gls4_decompose_digit_bounds_and_value():
    M = endo.GLS4_M
    for c in (0, 1, M - 1, M, M + 1, M * M, R - 1, (1 << 255) - 19,
              rng.randrange(1 << 255)):
        d = endo.gls4_decompose(c)
        assert len(d) == 4
        assert all(0 <= dk < M for dk in d)
        assert all(dk.bit_length() <= endo.GLS4_DIGIT_BITS for dk in d)
        got = sum(dk * M ** k for k, dk in enumerate(d))
        assert got == c % R


def test_gls4_basis_realizes_digit_multiplication():
    """Σ d_k · basis_k == c·P on the subgroup, for edge and random
    scalars — the identity the GLS-split recover ladders rely on."""
    g = PointG2.generator().mul(rng.randrange(1, R))
    basis = endo.gls4_points_from_affine(*g.to_affine())
    assert basis[0] == g
    M = endo.GLS4_M
    # the basis points ARE the [M^k] multiples
    assert basis[1] == -endo.psi(g)
    assert basis[2] == endo.psi2(g)
    assert basis[3] == -endo.psi3(g)
    for c in (1, M - 1, M + 1, R - 1, rng.randrange(1 << 255)):
        d = endo.gls4_decompose(c)
        acc = PointG2.infinity()
        for b, dk in zip(basis, d):
            acc = acc + b.mul(dk)
        assert acc == g.mul(c % R), c
