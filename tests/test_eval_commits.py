"""Batched commitment evaluation (DKG deal verification) and the
scan-MSM — device paths vs the host oracle.

Reference: kyber vss deal verification (g·s_i == Σ_k C_k·x^k), the
BASELINE "n=128 deal verify" config; engine.eval_commits is the device
call the DKG's _process_deals batches into.
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.device

from drand_tpu.crypto import batch
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.fields import R
from drand_tpu.crypto.poly import PriPoly, PubPoly


@pytest.fixture
def engine():
    from drand_tpu.ops.engine import BatchedEngine

    return BatchedEngine()


def test_eval_commits_matches_host(engine):
    rnd = random.Random(7)
    g = PointG1.generator()
    t, n = 5, 40
    polys = [PubPoly([g.mul(rnd.randrange(1, 2 ** 64)) for _ in range(t)])
             for _ in range(n)]
    idx = 11
    got = engine.eval_commits(polys, idx)
    exp = [p.eval(idx).value for p in polys]
    assert got == exp


def test_eval_commits_share_check_roundtrip(engine):
    # the actual DKG use: dealer polys, our decrypted share, g·s == eval
    t, n, my_index = 4, 9, 2
    pris = [PriPoly.random(t, seed=b"ec-%d" % d) for d in range(n)]
    pubs = [p.commit() for p in pris]
    shares = [p.eval(my_index).value for p in pris]
    evals = engine.eval_commits(pubs, my_index)
    g = PointG1.generator()
    assert all(g.mul(s) == e for s, e in zip(shares, evals))
    # a corrupted share must not check out
    assert g.mul((shares[0] + 1) % R) != evals[0]


def test_eval_commits_via_batch_dispatch():
    prev = batch._MODE, batch._MIN_BATCH
    try:
        batch.configure("device", min_batch=1)
        g = PointG1.generator()
        polys = [PubPoly([g.mul(3 + d + k) for k in range(3)])
                 for d in range(6)]
        got = batch.eval_commits(polys, 1)
        assert got == [p.eval(1).value for p in polys]
    finally:
        batch.configure(prev[0], min_batch=prev[1])


def test_msm_scan_and_lanes_match_unrolled():
    import jax.numpy as jnp

    from drand_tpu.ops import curve, limb
    from drand_tpu.ops.engine import _g2_aff
    from drand_tpu.crypto.fields import Fp2

    rnd = random.Random(3)
    n = 5
    pts_h = [PointG2.generator().mul(rnd.randrange(1, R)) for _ in range(n)]
    scals = [rnd.randrange(R) for _ in range(n)]
    exp = None
    for p, s in zip(pts_h, scals):
        q = p.mul(s)
        exp = q if exp is None else exp + q
    pts_np = np.stack([_g2_aff(p) for p in pts_h])
    z_one = np.zeros((n, 2, limb.NLIMBS), np.int32)
    z_one[:, 0] = np.asarray(limb.ONE_MONT)
    bits = np.stack([curve.scalar_to_bits(s, 255) for s in scals])
    pts = (jnp.asarray(pts_np[:, 0]), jnp.asarray(pts_np[:, 1]),
           jnp.asarray(z_one), jnp.asarray(np.zeros(n, bool)))
    ax, ay, is_inf = curve.pt_to_affine(
        curve.F2, curve.msm_scan(curve.F2, pts, jnp.asarray(bits)))
    got = PointG2(
        Fp2(limb.fp_from_device(np.asarray(ax)[0]),
            limb.fp_from_device(np.asarray(ax)[1])),
        Fp2(limb.fp_from_device(np.asarray(ay)[0]),
            limb.fp_from_device(np.asarray(ay)[1])),
        Fp2.one())
    assert not bool(np.asarray(is_inf))
    assert got == exp


def test_verify_bls_async_chunking(engine):
    """Batches beyond the largest bucket dispatch as multiple async
    launches and drain once — results must match per-row truth."""
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.hash_to_curve import hash_to_g2

    sk = 0xBEE
    pub = PointG1.generator().mul(sk)
    triples = []
    want = []
    for i in range(11):
        m = b"chunk-%d" % i
        sig = PointG2.from_bytes(bls.sign(sk, m), subgroup_check=False)
        if i % 3 == 2:  # wrong message for this signature
            triples.append((pub, sig, hash_to_g2(b"other")))
            want.append(False)
        else:
            triples.append((pub, sig, hash_to_g2(m)))
            want.append(True)
    small = type(engine)(buckets=(4,))
    out = small.verify_bls(triples)
    assert list(out) == want


def test_eval_poly_indices_matches_host(engine):
    from drand_tpu.crypto.poly import PriPoly

    poly = PriPoly.random(6, seed=b"epi").commit()
    idxs = [0, 2, 9, 33, 5]
    got = engine.eval_poly_indices(poly, idxs)
    assert got == [poly.eval(i).value for i in idxs]


def test_verify_partials_uses_batched_evals(engine):
    from drand_tpu.crypto import tbls
    from drand_tpu.crypto.poly import PriPoly

    pri = PriPoly.random(3, seed=b"vp")
    pub = pri.commit()
    msg = b"round-msg"
    partials = [tbls.sign_partial(s, msg) for s in pri.shares(7)]
    oks = engine.verify_partials(pub, msg, partials)
    assert oks == [True] * 7
    bad = bytearray(partials[2])
    bad[-1] ^= 1
    oks = engine.verify_partials(pub, msg, [bytes(bad)] + partials[:2])
    assert oks == [False, True, True]


def test_msm_lanes_matches_host():
    import jax.numpy as jnp

    from drand_tpu.ops import curve, limb
    from drand_tpu.ops.engine import _g2_aff
    from drand_tpu.crypto.fields import Fp2

    rnd = random.Random(5)
    n = 8  # power of two incl. masked (infinity) pad lanes
    pts_h = [PointG2.generator().mul(rnd.randrange(1, R)) for _ in range(6)]
    scals = [rnd.randrange(R) for _ in range(6)]
    exp = None
    for p, s in zip(pts_h, scals):
        q = p.mul(s)
        exp = q if exp is None else exp + q
    pts_np = np.stack([_g2_aff(p) for p in pts_h] +
                      [_g2_aff(PointG2.generator())] * 2)
    z_one = np.zeros((n, 2, limb.NLIMBS), np.int32)
    z_one[:, 0] = np.asarray(limb.ONE_MONT)
    inf = np.zeros(n, bool)
    inf[6:] = True  # pad lanes masked out
    bits = np.stack([curve.scalar_to_bits(s, 255) for s in scals] +
                    [np.zeros(255, np.int32)] * 2)
    pts = (jnp.asarray(pts_np[:, 0]), jnp.asarray(pts_np[:, 1]),
           jnp.asarray(z_one), jnp.asarray(inf))
    ax, ay, is_inf = curve.pt_to_affine(
        curve.F2, curve.msm_lanes(curve.F2, pts, jnp.asarray(bits)))
    got = PointG2(
        Fp2(limb.fp_from_device(np.asarray(ax)[0]),
            limb.fp_from_device(np.asarray(ax)[1])),
        Fp2(limb.fp_from_device(np.asarray(ay)[0]),
            limb.fp_from_device(np.asarray(ay)[1])),
        Fp2.one())
    assert not bool(np.asarray(is_inf))
    assert got == exp
