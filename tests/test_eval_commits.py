"""Batched commitment evaluation (DKG deal verification) and the
scan-MSM — device paths vs the host oracle.

Reference: kyber vss deal verification (g·s_i == Σ_k C_k·x^k), the
BASELINE "n=128 deal verify" config; engine.eval_commits is the device
call the DKG's _process_deals batches into.
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.device

from drand_tpu.crypto import batch
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.fields import R
from drand_tpu.crypto.poly import PriPoly, PubPoly


@pytest.fixture
def engine():
    from drand_tpu.ops.engine import BatchedEngine

    return BatchedEngine()


def test_eval_commits_matches_host(engine):
    rnd = random.Random(7)
    g = PointG1.generator()
    t, n = 5, 40
    polys = [PubPoly([g.mul(rnd.randrange(1, 2 ** 64)) for _ in range(t)])
             for _ in range(n)]
    idx = 11
    got = engine.eval_commits(polys, idx)
    exp = [p.eval(idx).value for p in polys]
    assert got == exp


def test_eval_commits_share_check_roundtrip(engine):
    # the actual DKG use: dealer polys, our decrypted share, g·s == eval
    t, n, my_index = 4, 9, 2
    pris = [PriPoly.random(t, seed=b"ec-%d" % d) for d in range(n)]
    pubs = [p.commit() for p in pris]
    shares = [p.eval(my_index).value for p in pris]
    evals = engine.eval_commits(pubs, my_index)
    g = PointG1.generator()
    assert all(g.mul(s) == e for s, e in zip(shares, evals))
    # a corrupted share must not check out
    assert g.mul((shares[0] + 1) % R) != evals[0]


def test_eval_commits_via_batch_dispatch():
    prev = batch._MODE, batch._MIN_BATCH
    try:
        batch.configure("device", min_batch=1)
        g = PointG1.generator()
        polys = [PubPoly([g.mul(3 + d + k) for k in range(3)])
                 for d in range(6)]
        got = batch.eval_commits(polys, 1)
        assert got == [p.eval(1).value for p in polys]
    finally:
        batch.configure(prev[0], min_batch=prev[1])


def test_msm_scan_and_lanes_match_unrolled():
    import jax.numpy as jnp

    from drand_tpu.ops import curve, limb
    from drand_tpu.ops.engine import _g2_aff
    from drand_tpu.crypto.fields import Fp2

    rnd = random.Random(3)
    n = 5
    pts_h = [PointG2.generator().mul(rnd.randrange(1, R)) for _ in range(n)]
    scals = [rnd.randrange(R) for _ in range(n)]
    exp = None
    for p, s in zip(pts_h, scals):
        q = p.mul(s)
        exp = q if exp is None else exp + q
    pts_np = np.stack([_g2_aff(p) for p in pts_h])
    z_one = np.zeros((n, 2, limb.NLIMBS), np.int32)
    z_one[:, 0] = np.asarray(limb.ONE_MONT)
    bits = np.stack([curve.scalar_to_bits(s, 255) for s in scals])
    pts = (jnp.asarray(pts_np[:, 0]), jnp.asarray(pts_np[:, 1]),
           jnp.asarray(z_one), jnp.asarray(np.zeros(n, bool)))
    ax, ay, is_inf = curve.pt_to_affine(
        curve.F2, curve.msm_scan(curve.F2, pts, jnp.asarray(bits)))
    got = PointG2(
        Fp2(limb.fp_from_device(np.asarray(ax)[0]),
            limb.fp_from_device(np.asarray(ax)[1])),
        Fp2(limb.fp_from_device(np.asarray(ay)[0]),
            limb.fp_from_device(np.asarray(ay)[1])),
        Fp2.one())
    assert not bool(np.asarray(is_inf))
    assert got == exp


def test_verify_bls_async_chunking(engine):
    """Batches beyond the largest bucket dispatch as multiple async
    launches and drain once — results must match per-row truth."""
    from drand_tpu.crypto import bls
    from drand_tpu.crypto.hash_to_curve import hash_to_g2

    sk = 0xBEE
    pub = PointG1.generator().mul(sk)
    triples = []
    want = []
    for i in range(11):
        m = b"chunk-%d" % i
        sig = PointG2.from_bytes(bls.sign(sk, m), subgroup_check=False)
        if i % 3 == 2:  # wrong message for this signature
            triples.append((pub, sig, hash_to_g2(b"other")))
            want.append(False)
        else:
            triples.append((pub, sig, hash_to_g2(m)))
            want.append(True)
    small = type(engine)(buckets=(4,))
    out = small.verify_bls(triples)
    assert list(out) == want


def test_eval_poly_indices_matches_host(engine):
    from drand_tpu.crypto.poly import PriPoly

    poly = PriPoly.random(6, seed=b"epi").commit()
    idxs = [0, 2, 9, 33, 5]
    got = engine.eval_poly_indices(poly, idxs)
    assert got == [poly.eval(i).value for i in idxs]


def test_verify_partials_uses_batched_evals(engine):
    from drand_tpu.crypto import tbls
    from drand_tpu.crypto.poly import PriPoly

    pri = PriPoly.random(3, seed=b"vp")
    pub = pri.commit()
    msg = b"round-msg"
    partials = [tbls.sign_partial(s, msg) for s in pri.shares(7)]
    oks = engine.verify_partials(pub, msg, partials)
    assert oks == [True] * 7
    bad = bytearray(partials[2])
    bad[-1] ^= 1
    oks = engine.verify_partials(pub, msg, [bytes(bad)] + partials[:2])
    assert oks == [False, True, True]


def test_msm_lanes_matches_host():
    import jax.numpy as jnp

    from drand_tpu.ops import curve, limb
    from drand_tpu.ops.engine import _g2_aff
    from drand_tpu.crypto.fields import Fp2

    rnd = random.Random(5)
    n = 8  # power of two incl. masked (infinity) pad lanes
    pts_h = [PointG2.generator().mul(rnd.randrange(1, R)) for _ in range(6)]
    scals = [rnd.randrange(R) for _ in range(6)]
    exp = None
    for p, s in zip(pts_h, scals):
        q = p.mul(s)
        exp = q if exp is None else exp + q
    pts_np = np.stack([_g2_aff(p) for p in pts_h] +
                      [_g2_aff(PointG2.generator())] * 2)
    z_one = np.zeros((n, 2, limb.NLIMBS), np.int32)
    z_one[:, 0] = np.asarray(limb.ONE_MONT)
    inf = np.zeros(n, bool)
    inf[6:] = True  # pad lanes masked out
    bits = np.stack([curve.scalar_to_bits(s, 255) for s in scals] +
                    [np.zeros(255, np.int32)] * 2)
    pts = (jnp.asarray(pts_np[:, 0]), jnp.asarray(pts_np[:, 1]),
           jnp.asarray(z_one), jnp.asarray(inf))
    ax, ay, is_inf = curve.pt_to_affine(
        curve.F2, curve.msm_lanes(curve.F2, pts, jnp.asarray(bits)))
    got = PointG2(
        Fp2(limb.fp_from_device(np.asarray(ax)[0]),
            limb.fp_from_device(np.asarray(ax)[1])),
        Fp2(limb.fp_from_device(np.asarray(ay)[0]),
            limb.fp_from_device(np.asarray(ay)[1])),
        Fp2.one())
    assert not bool(np.asarray(is_inf))
    assert got == exp


def test_horner_bl_matches_host():
    """The batch-last Horner body behind the Pallas deal-verify kernel
    (ops/pallas_eval.horner_bl), run on the XLA path: Jacobian output
    converted on host must equal every dealer's PubPoly.eval."""
    import jax.numpy as jnp

    from drand_tpu.ops import bl_curve, curve as xcurve, limb, pallas_eval
    from drand_tpu.ops.engine import BatchedEngine, _g1_xy
    from drand_tpu.ops.pallas_pairing import value_bit_getter

    t, b, index = 3, 4, 6
    g = PointG1.generator()
    polys = [PubPoly([g.mul(97 * d + 13 * k + 1) for k in range(t)])
             for d in range(b)]
    xs = np.zeros((t, limb.NLIMBS, b), np.int32)
    ys = np.zeros((t, limb.NLIMBS, b), np.int32)
    flat = PointG1.batch_to_affine([c for p in polys for c in p.commits])
    for d in range(b):
        for k in range(t):
            aff = _g1_xy(flat[d * t + k])
            xs[k, :, d], ys[k, :, d] = aff[0], aff[1]
    bits = xcurve.scalar_to_bits(index + 1, pallas_eval.NBITS)
    F = bl_curve.make_f1()
    import jax

    xs_j, ys_j = jnp.asarray(xs), jnp.asarray(ys)

    def get_commit(k):  # k is traced inside fori_loop on the XLA path
        return (jax.lax.dynamic_index_in_dim(xs_j, k, 0, keepdims=False),
                jax.lax.dynamic_index_in_dim(ys_j, k, 0, keepdims=False))

    X, Y, Z, inf32 = pallas_eval.horner_bl(
        F, get_commit, value_bit_getter(jnp.asarray(bits)[None, :]), t, b)
    # batch-last -> batch-leading rows, then the engine's host unpack
    rows = np.concatenate(
        [np.asarray(X).T, np.asarray(Y).T, np.asarray(Z).T,
         np.asarray(inf32)[:, None]], axis=1)
    got = BatchedEngine._unpack_eval_host(rows, 3, b)
    exp = [p.eval(index).value for p in polys]
    assert got == exp


def test_unpack_eval_jacobian_infinity_row():
    """Jacobian host unpack: z=0 / inf-flagged rows come back as the
    point at infinity; finite rows convert exactly."""
    from drand_tpu.ops import limb
    from drand_tpu.ops.engine import BatchedEngine
    from drand_tpu.crypto.fields import P as _P

    g = PointG1.generator()
    x, y = g.to_affine()
    z = 12345
    # jacobian (X, Y, Z) = (x z^2, y z^3, z)
    X = limb.int_to_mont_limbs(x.v * z * z % _P)
    Y = limb.int_to_mont_limbs(y.v * z * z % _P * z % _P)
    Z = limb.int_to_mont_limbs(z)
    zero = np.zeros(limb.NLIMBS, np.int32)
    rows = np.stack([
        np.concatenate([X, Y, Z, [0]]),
        np.concatenate([zero, zero, zero, [1]]),
    ]).astype(np.int32)
    got = BatchedEngine._unpack_eval_host(rows, 3, 2)
    assert got[0] == g
    assert got[1].is_infinity()


def test_msm_fold_bl_matches_host():
    """The batch-last ladder + lane-roll log-fold + to-affine behind the
    Pallas recovery MSM (ops/pallas_msm), on the XLA path: lane 0 must
    equal the host Σ s_i·P_i, with padding lanes masked as infinity."""
    import jax
    import jax.numpy as jnp

    from drand_tpu.crypto.fields import Fp2
    from drand_tpu.ops import bl_curve, curve as xcurve, limb, pallas_msm
    from drand_tpu.ops.engine import _g2_aff

    rnd = random.Random(3)
    b, nbits = 8, 48
    pts = [PointG2.generator().mul(rnd.randrange(1, 1 << 40))
           for _ in range(b - 3)]
    scalars = [rnd.randrange(1, 1 << nbits) for _ in pts]
    arr = np.zeros((b, 2, 2, limb.NLIMBS), np.int32)
    inf = np.ones(b, bool)
    bits = np.zeros((b, nbits), np.int32)
    for i, (p, s) in enumerate(zip(pts, scalars)):
        arr[i] = _g2_aff(p)
        inf[i] = False
        bits[i] = xcurve.scalar_to_bits(s, nbits)
    F = bl_curve.F2
    xq = jnp.moveaxis(jnp.asarray(arr[:, 0]), 0, -1)   # (2, 32, b)
    yq = jnp.moveaxis(jnp.asarray(arr[:, 1]), 0, -1)
    bits_bl = jnp.asarray(bits.T)                      # (nbits, b)

    def bit_getter(i):
        return jax.lax.dynamic_slice_in_dim(bits_bl, i, 1, 0)[0]

    acc = bl_curve.pt_mul_bits_getter(
        F, (xq, yq, F.one((b,)), jnp.asarray(inf)), bit_getter, nbits)
    ax, ay, ainf = xcurve.pt_to_affine(
        F, pallas_msm.msm_fold_bl(F, acc, b))
    ax, ay = np.asarray(ax)[..., 0], np.asarray(ay)[..., 0]
    assert not bool(np.asarray(ainf)[0])
    got = PointG2(
        Fp2(limb.fp_from_device(ax[0]), limb.fp_from_device(ax[1])),
        Fp2(limb.fp_from_device(ay[0]), limb.fp_from_device(ay[1])),
        Fp2.one())
    exp = PointG2.infinity()
    for p, s in zip(pts, scalars):
        exp = exp + p.mul(s)
    assert got == exp
