"""Mock beacon server (real signatures + corruption switches) and the
BASELINE config-1 scale test: 3-of-5 beacon over 100 rounds.

Reference: test/mock/grpcserver.go:184-238 (mock with corruption),
BASELINE.md config 1 (demo-style 3-of-5 x 100 rounds).
"""

import pytest

pytestmark = pytest.mark.slow

from drand_tpu.chain.beacon import verify_beacon, verify_beacon_v2
from drand_tpu.client import ClientError, new_client
from drand_tpu.crypto import batch
from drand_tpu.testing.harness import BeaconTestNetwork
from drand_tpu.testing.mock_server import MockBeaconServer


@pytest.mark.asyncio
async def test_mock_server_chain_is_real():
    mock = MockBeaconServer(nrounds=6)
    pub = mock.chain_info.public_key
    for rnd in range(1, 7):
        b = mock.beacons[rnd]
        assert verify_beacon(pub, b)
        assert verify_beacon_v2(pub, b)
    # the verified client stack accepts it end to end (strict chain walk)
    client = new_client([mock], chain_info=mock.chain_info, strict_rounds=True)
    r = await client.get(6)
    assert r.round == 6


@pytest.mark.asyncio
async def test_mock_server_corruption_switch():
    mock = MockBeaconServer(nrounds=5, bad_second_round=True)
    client = new_client([mock], chain_info=mock.chain_info)
    assert (await client.get(3)).round == 3
    with pytest.raises(ClientError):
        await client.get(2)
    # strict mode: the corrupted round poisons later rounds' history walk
    strict = new_client([mock], chain_info=mock.chain_info, strict_rounds=True)
    with pytest.raises(ClientError):
        await strict.get(5)


@pytest.mark.asyncio
async def test_mock_server_emit_extends_chain():
    mock = MockBeaconServer(nrounds=3)
    b = mock.emit()
    assert b.round == 4
    assert verify_beacon(mock.chain_info.public_key, b)
    assert (await mock.get(0)).round == 4


@pytest.mark.asyncio
async def test_3of5_100_rounds():
    """BASELINE config 1 at protocol level: n=5 t=3, 100 rounds, full
    chain verified at the end in one batched pass (host dispatch — the
    engine/host agreement is pinned by test_batch_engine; this test is
    about protocol scale, not the engine)."""
    import drand_tpu.crypto.batch as b

    old = (b._MODE, b._MIN_BATCH, b._ENGINE)
    b.configure("host")
    net = BeaconTestNetwork(n=5, t=3, period=4)
    await net.start_all()
    await net.advance_to_genesis()
    for _ in range(100):
        await net.clock.advance(4)
    for i in range(5):
        await net.wait_round(i, 100, timeout=120)
    net.stop_all()
    try:
        pub = net.group.public_key.key()
        ref = [net.nodes[0].store.get(r) for r in range(1, 101)]
        oks = batch.verify_beacons(pub, ref)
        assert oks.all()
        # every node converged on the identical chain
        for node in net.nodes[1:]:
            for r in (1, 50, 100):
                assert node.store.get(r).signature == ref[r - 1].signature
    finally:
        b._MODE, b._MIN_BATCH, b._ENGINE = old
