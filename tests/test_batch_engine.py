"""Batched device engine vs host-reference agreement, and engine-routed
protocol paths.

The host implementation (crypto/) is the semantics oracle; every engine
operation must agree with it bit-for-bit, including on malformed and
corrupted inputs. This is the integration guarantee VERDICT r1 flagged as
missing: the TPU engine wired into the aggregator (chain/beacon/chain.go:136
analogue) and the syncer (client/verify.go:146 analogue).
"""

import os

import numpy as np
import pytest

pytestmark = pytest.mark.device

from drand_tpu.chain.beacon import Beacon, message, message_v2
from drand_tpu.crypto import batch, bls, tbls
from drand_tpu.crypto.curves import PointG1
from drand_tpu.crypto.poly import PriPoly


TINY_BUCKETS = (1, 2, 4)  # bound compile count in the suite


@pytest.fixture(scope="module")
def engine():
    from drand_tpu.ops.engine import BatchedEngine

    return BatchedEngine(buckets=TINY_BUCKETS)


@pytest.fixture()
def device_mode(engine):
    """Force all batch.* dispatch through the device engine."""
    import drand_tpu.crypto.batch as b

    old = (b._MODE, b._MIN_BATCH, b._ENGINE)
    b.configure("device", min_batch=1, engine=engine)
    yield
    b._MODE, b._MIN_BATCH, b._ENGINE = old


@pytest.fixture(scope="module")
def threshold_setup():
    poly = PriPoly.random(2, seed=b"batch-engine-test")
    pub = poly.commit()
    shares = poly.shares(3)
    sk = poly.secret()
    pubkey = PointG1.generator().mul(sk)
    return poly, pub, shares, sk, pubkey


def _make_chain(sk: int, nrounds: int, v2: bool = True) -> list[Beacon]:
    prev = b"\x42" * 32
    out = []
    for rnd in range(1, nrounds + 1):
        sig = bls.sign(sk, message(rnd, prev))
        sig2 = bls.sign(sk, message_v2(rnd)) if v2 else b""
        out.append(Beacon(round=rnd, previous_sig=prev, signature=sig,
                          signature_v2=sig2))
        prev = sig
    return out


class TestEngineVsHost:
    def test_verify_partials_valid_and_corrupt(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-7"
        partials = [tbls.sign_partial(s, msg) for s in shares]
        assert engine.verify_partials(pub, msg, partials) == [True] * 3
        # flip one byte of the signature body of partial 1
        bad = partials[1][:5] + bytes([partials[1][5] ^ 1]) + partials[1][6:]
        got = engine.verify_partials(pub, msg, [partials[0], bad, partials[2]])
        host = [tbls.verify_partial(pub, msg, p)
                for p in (partials[0], bad, partials[2])]
        assert got == host == [True, False, True]

    def test_verify_partials_malformed(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-8"
        good = tbls.sign_partial(shares[0], msg)
        garbage = [b"", b"\x00" * 98, good[:50]]
        got = engine.verify_partials(pub, msg, [good] + garbage)
        assert got == [True, False, False, False]

    def test_recover_matches_host(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-9"
        partials = [tbls.sign_partial(s, msg) for s in shares]
        # every 2-subset recovers the same signature as the host
        for subset in ([0, 1], [1, 2], [0, 2], [2, 1, 0]):
            ps = [partials[i] for i in subset]
            assert engine.recover(pub, msg, ps, 2, 3) == \
                tbls.recover(pub, msg, ps, 2, 3)

    def test_recover_not_enough(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-10"
        partials = [tbls.sign_partial(shares[0], msg)]
        with pytest.raises(ValueError):
            engine.recover(pub, msg, partials, 2, 3)

    def test_aggregate_round_fused(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-agg"
        partials = [tbls.sign_partial(s, msg) for s in shares]
        oks, sig = engine.aggregate_round(pub, msg, partials, 2, 3)
        assert oks == [True] * 3
        assert sig == tbls.recover(pub, msg, partials, 2, 3)
        # the fused executable (bucket 4, 8 msm lanes — the GLS4 split
        # packs 4 digit lanes per share at 64-bit width) must have
        # passed its KAT — i.e. this went through ONE dispatch, not the
        # fallback
        from drand_tpu.crypto.endo import GLS4_DIGIT_BITS

        assert engine.agg_shape(3, 2) == (4, 8, GLS4_DIGIT_BITS)
        assert engine._agg_ok.get((4, 8, GLS4_DIGIT_BITS)) is True

    def test_aggregate_round_bad_chosen_partial(self, engine,
                                                threshold_setup):
        # a corrupt partial inside the optimistic t-subset: flagged in
        # oks, and recovery re-runs over the verified survivors
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-agg-bad"
        partials = [tbls.sign_partial(s, msg) for s in shares]
        bad = partials[0][:5] + bytes([partials[0][5] ^ 1]) + partials[0][6:]
        oks, sig = engine.aggregate_round(
            pub, msg, [bad, partials[1], partials[2]], 2, 3)
        assert oks == [False, True, True]
        assert sig == tbls.recover(pub, msg, partials[1:], 2, 3)

    def test_aggregate_round_not_enough(self, engine, threshold_setup):
        _, pub, shares, _, _ = threshold_setup
        msg = b"round-agg-short"
        with pytest.raises(ValueError):
            engine.aggregate_round(
                pub, msg, [tbls.sign_partial(shares[0], msg)], 2, 3)

    def test_verify_beacons_dual(self, engine, threshold_setup):
        *_, sk, pubkey = threshold_setup
        beacons = _make_chain(sk, 3)
        assert engine.verify_beacons(pubkey, beacons).all()
        # corrupting the V2 signature must fail exactly that beacon
        beacons[1].signature_v2 = beacons[0].signature_v2
        got = engine.verify_beacons(pubkey, beacons)
        assert list(got) == [True, False, True]

    def test_verify_beacons_v1_corruption(self, engine, threshold_setup):
        *_, sk, pubkey = threshold_setup
        beacons = _make_chain(sk, 5, v2=False)  # 5 > top bucket: splits
        beacons[3].signature = beacons[2].signature
        got = engine.verify_beacons(pubkey, beacons)
        assert list(got) == [True, True, True, False, True]


class TestBatchDispatch:
    def test_host_and_device_agree(self, threshold_setup, device_mode):
        *_, sk, pubkey = threshold_setup
        beacons = _make_chain(sk, 3)
        dev = batch.verify_beacons(pubkey, beacons)
        import drand_tpu.crypto.batch as b

        b.configure("host")
        host = batch.verify_beacons(pubkey, beacons)
        assert list(dev) == list(host) == [True, True, True]

    def test_aggregate_round_host_path(self, threshold_setup):
        import drand_tpu.crypto.batch as b

        _, pub, shares, *_ = threshold_setup
        msg = b"agg-host"
        partials = [tbls.sign_partial(s, msg) for s in shares]
        old = (b._MODE, b._MIN_BATCH, b._ENGINE)
        b.configure("host")
        try:
            oks, sig = batch.aggregate_round(pub, msg, partials, 2, 3)
        finally:
            b._MODE, b._MIN_BATCH, b._ENGINE = old
        assert oks == [True] * 3
        assert sig == tbls.recover(pub, msg, partials, 2, 3)

    def test_verify_recovered_many(self, threshold_setup, device_mode):
        _, pub, shares, sk, pubkey = threshold_setup
        m1, m2 = message(1, b"\x42" * 32), message_v2(1)
        s1, s2 = bls.sign(sk, m1), bls.sign(sk, m2)
        assert batch.verify_recovered_many(pubkey, [(m1, s1), (m2, s2)]) == \
            [True, True]
        assert batch.verify_recovered_many(pubkey, [(m1, s2), (m2, s2)]) == \
            [False, True]


@pytest.mark.skipif(os.environ.get("DRAND_TPU_HEAVY_TESTS") != "1",
                    reason="one large-batch compile (~minutes cold); set "
                           "DRAND_TPU_HEAVY_TESTS=1 to run")
def test_batch64_regression(threshold_setup):
    """Batch >= 64 regression: lax.cond/lax.switch inside lax.scan
    miscompiled on the axon TPU backend (all checks returned wrong results
    at B=64 while B=16 passed). The pairing is now cond-free; this pins it
    at a batch size above the failure threshold on whatever backend the
    suite runs."""
    from drand_tpu.ops.engine import BatchedEngine

    *_, sk, pubkey = threshold_setup
    eng = BatchedEngine(buckets=(64,))
    beacons = _make_chain(sk, 8, v2=True)  # 16 checks padded to 64
    got = eng.verify_beacons(pubkey, beacons)
    assert got.all()
    beacons[5].signature = beacons[4].signature
    got = eng.verify_beacons(pubkey, beacons)
    assert list(got) == [True] * 5 + [False] + [True] * 2


@pytest.mark.asyncio
async def test_beacon_network_with_device_engine(device_mode):
    """End-to-end: a 3-node t=2 network produces verifying rounds with every
    crypto call routed through the device engine (the aggregator's recover +
    verify and the handler's partial checks all go through batch.*)."""
    from drand_tpu.chain import beacon as chain_beacon
    from drand_tpu.testing.harness import BeaconTestNetwork

    net = BeaconTestNetwork(n=3, t=2, period=2)
    await net.start_all()
    await net.advance_to_genesis()
    # per-round lockstep (the test_beacon_engine idiom): aggregation runs
    # off-loop in a thread, so each round must land before the fake clock
    # moves on — advancing several periods at once parks every node in the
    # catchup breather, which sleeps on the (now idle) fake clock forever
    for r in range(1, 4):
        for i in range(3):
            await net.wait_round(i, r, timeout=120)
        await net.advance_rounds(1)
    net.stop_all()
    pubkey = net.group.public_key.key()
    for node in net.nodes:
        beacons = [node.store.get(r) for r in range(1, 4)]
        assert batch.verify_beacons(pubkey, beacons).all()
        for b in beacons:
            assert chain_beacon.verify_beacon(pubkey, b)
