"""Golden tests: the device engine (drand_tpu.ops) against the host
reference (drand_tpu.crypto).

Covers VERDICT r1 items: ops/ had zero tests; the optimization_barrier
miscompile regression (jit == eager for the tower); the pairing path that
had never completed a run. Runs on the CPU backend (conftest forces
JAX_PLATFORMS=cpu with a persistent compile cache).

Reference parity: the host crypto is itself golden-tested against RFC 9380
vectors and kyber wire formats (tests/test_crypto_core.py), mirroring the
reference's crypto usage sites (/root/reference/key/curve.go:19-38,
/root/reference/chain/beacon/chain.go:136-166).
"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.device

import jax
import jax.numpy as jnp

from drand_tpu.crypto.fields import P, Fp2, Fp6, Fp12, XI
from drand_tpu.crypto import curves as hc
from drand_tpu.crypto import pairing as hp
from drand_tpu.crypto.hash_to_curve import hash_to_g2
from drand_tpu.ops import limb, tower, curve, pairing as dpair


rnd = random.Random(0xD5A)


def rfp() -> int:
    return rnd.randrange(P)


def rf2() -> Fp2:
    return Fp2(rfp(), rfp())


def rf12() -> Fp12:
    return Fp12(Fp6(rf2(), rf2(), rf2()), Fp6(rf2(), rf2(), rf2()))


# ---------------------------------------------------------------------------
# Limb layer
# ---------------------------------------------------------------------------

class TestLimb:
    def test_roundtrip(self):
        for _ in range(10):
            x = rfp()
            assert limb.fp_from_device(limb.fp_to_device(x)) == x

    def test_mont_mul_golden_jit_vs_eager(self):
        """The optimization_barrier regression guard: jit and eager must
        agree with the host product (ops/limb.py mont_mul docstring)."""
        mulj = jax.jit(limb.mont_mul)
        for i in range(12):
            a, b = rfp(), rfp()
            ad, bd = limb.fp_to_device(a), limb.fp_to_device(b)
            exp = a * b % P
            assert limb.fp_from_device(mulj(ad, bd)) == exp
            if i < 1:  # eager path once is enough (dispatch-slow)
                assert limb.fp_from_device(limb.mont_mul(ad, bd)) == exp

    def test_add_sub_fuzz_including_high_values(self):
        """reduce_light truncation-edge regression: biased-high limbs near
        2^384 exercise the second wrap pass (a real 0.4% bug when absent)."""
        n = 4096
        rng = np.random.default_rng(11)
        A = rng.integers(0, 4098, size=(n, 32), dtype=np.int32)
        B = rng.integers(0, 4098, size=(n, 32), dtype=np.int32)
        A[: n // 2, -8:] = 4096
        B[: n // 2, -8:] = 4096
        out_add = np.asarray(jax.jit(limb.add)(A, B))
        out_sub = np.asarray(jax.jit(limb.sub)(A, B))
        for i in range(n):
            va, vb = limb.limbs_to_int(A[i]), limb.limbs_to_int(B[i])
            assert limb.limbs_to_int(out_add[i]) % P == (va + vb) % P
            assert limb.limbs_to_int(out_sub[i]) % P == (va - vb) % P
            assert out_add[i].max() <= 4200 and out_sub[i].max() <= 4200

    def test_adversarial_reduce(self):
        for pattern in (4096, 4097, 4112, 8194):
            t = jnp.full((32,), pattern, jnp.int32)
            out = limb.reduce_limbs(t)
            assert limb.limbs_to_int(np.asarray(out)) % P == \
                limb.limbs_to_int(np.asarray(t)) % P
            t2 = jnp.full((32,), min(pattern, 8190), jnp.int32)
            out2 = limb.reduce_light(t2)
            assert limb.limbs_to_int(np.asarray(out2)) % P == \
                limb.limbs_to_int(np.asarray(t2)) % P

    def test_inv(self):
        for _ in range(3):
            x = rfp()
            got = limb.fp_from_device(jax.jit(limb.inv)(limb.fp_to_device(x)))
            assert got == pow(x, P - 2, P)

    def test_is_zero_mod_p(self):
        assert bool(limb.is_zero_mod_p(limb.fp_to_device(0)))
        assert bool(limb.is_zero_mod_p(jnp.asarray(limb.int_to_limbs(P))))
        assert not bool(limb.is_zero_mod_p(limb.fp_to_device(1)))


# ---------------------------------------------------------------------------
# Tower layer
# ---------------------------------------------------------------------------

class TestTower:
    def test_f2_ops(self):
        mulj = jax.jit(tower.f2_mul)
        addj = jax.jit(tower.f2_add)
        subj = jax.jit(tower.f2_sub)
        sqrj = jax.jit(tower.f2_sqr)
        xij = jax.jit(tower.f2_mul_by_xi)
        for _ in range(8):
            x, y = rf2(), rf2()
            xd, yd = tower.fp2_to_device(x), tower.fp2_to_device(y)
            assert tower.fp2_from_device(mulj(xd, yd)) == x * y
            assert tower.fp2_from_device(addj(xd, yd)) == x + y
            assert tower.fp2_from_device(subj(xd, yd)) == x - y
            assert tower.fp2_from_device(sqrj(xd)) == x * x
            assert tower.fp2_from_device(xij(xd)) == x * XI

    def test_f2_inv(self):
        x = rf2()
        xd = tower.fp2_to_device(x)
        assert tower.fp2_from_device(jax.jit(tower.f2_inv)(xd)) == x.inverse()

    def test_f12_mul_jit_vs_eager_barrier_regression(self):
        """jit(f12_mul) != eager f12_mul was the observed XLA miscompile the
        optimization_barrier in mont_mul guards against."""
        x, y = rf12(), rf12()
        xd, yd = tower.fp12_to_device(x), tower.fp12_to_device(y)
        eager = tower.fp12_from_device(tower.f12_mul(xd, yd))
        jitted = tower.fp12_from_device(jax.jit(tower.f12_mul)(xd, yd))
        assert eager == x * y
        assert jitted == x * y

    def test_f12_ops(self):
        x = rf12()
        xd = tower.fp12_to_device(x)
        assert tower.fp12_from_device(jax.jit(tower.f12_sqr)(xd)) == x * x
        assert tower.fp12_from_device(jax.jit(tower.f12_conj)(xd)) == \
            x.conjugate()
        for power in (1, 2, 3):
            frob = jax.jit(tower.f12_frobenius, static_argnums=1)
            assert tower.fp12_from_device(frob(xd, power)) == x.frobenius(power)

    def test_f12_inv(self):
        x = rf12()
        xd = tower.fp12_to_device(x)
        assert tower.fp12_from_device(jax.jit(tower.f12_inv)(xd)) == x.inverse()

    def test_cyclotomic_square(self):
        # project a random element into the cyclotomic subgroup first
        x = rf12()
        c = hp.final_exponentiation(x, canonical=False)
        cd = tower.fp12_to_device(c)
        assert tower.fp12_from_device(tower.f12_cyclotomic_sqr(cd)) == \
            c.cyclotomic_square()

    def test_batched_broadcasting(self):
        xs = [rf2() for _ in range(4)]
        ys = [rf2() for _ in range(4)]
        xd = jnp.stack([tower.fp2_to_device(x) for x in xs])
        yd = jnp.stack([tower.fp2_to_device(y) for y in ys])
        out = jax.jit(tower.f2_mul)(xd, yd)
        for i in range(4):
            assert tower.fp2_from_device(out[i]) == xs[i] * ys[i]


# ---------------------------------------------------------------------------
# Curve layer
# ---------------------------------------------------------------------------

class TestCurve:
    def test_g1_add_dbl_mul(self):
        g = hc.PointG1.generator()
        a, b = g.mul(7), g.mul(11)
        ad, bd = curve.g1_to_device(a), curve.g1_to_device(b)
        addj = jax.jit(lambda p, q: curve.pt_add(curve.F1, p, q))
        dblj = jax.jit(lambda p: curve.pt_dbl(curve.F1, p))
        assert curve.g1_from_device(addj(ad, bd)) == a + b
        assert curve.g1_from_device(dblj(ad)) == a.double()
        # exceptional cases
        assert curve.g1_from_device(addj(ad, ad)) == a.double()
        nd = curve.g1_to_device(-a)
        assert curve.g1_from_device(addj(ad, nd)).is_infinity()
        infd = curve.g1_to_device(hc.PointG1.infinity())
        assert curve.g1_from_device(addj(ad, infd)) == a

    def test_g2_add_mul_scan(self):
        g = hc.PointG2.generator()
        a = g.mul(5)
        ad = curve.g2_to_device(a)
        k = 0x1234567
        bits = jnp.asarray(curve.scalar_to_bits(k, 32))
        got = curve.g2_from_device(
            jax.jit(lambda p, b: curve.pt_mul_bits(curve.F2, p, b))(ad, bits))
        assert got == a.mul(k)

    def test_msm_matches_host(self):
        g = hc.PointG1.generator()
        pts = [g.mul(i + 3) for i in range(4)]
        scalars = [rnd.randrange(1 << 64) for _ in range(4)]
        ptd = curve.stack_points([curve.g1_to_device(p) for p in pts])
        bits = jnp.stack([jnp.asarray(curve.scalar_to_bits(s, 64))
                          for s in scalars])
        got = curve.g1_from_device(
            jax.jit(lambda p, b: curve.msm(curve.F1, p, b))(ptd, bits))
        exp = hc.PointG1.msm(scalars, pts)
        assert got == exp


# ---------------------------------------------------------------------------
# Pairing layer (the expensive compiles — kept to a handful of calls,
# amortized by the persistent compilation cache)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def verify_compiled():
    fn = jax.jit(dpair.verify_prepared)
    pub = hc.PointG1.generator().mul(42)
    sig = hash_to_g2(b"seed").mul(42)
    pub_d = dpair.g1_affine_to_device(pub)
    sig_d = dpair.g2_affine_to_device(sig)[None]
    return fn, pub_d, sig_d


class TestPairing:
    def test_pairing_matches_host_canonical(self):
        p = hc.PointG1.generator().mul(9)
        q = hash_to_g2(b"golden")
        p_d = dpair.g1_affine_to_device(p)
        q_d = dpair.g2_affine_to_device(q)[None]
        out = jax.jit(lambda a, b: dpair.multi_pairing(a, b, canonical=True))(
            (p_d[0][None], p_d[1][None]), q_d)
        assert tower.fp12_from_device(out) == hp.pairing(p, q)

    def test_final_exponentiation_matches_host(self):
        x = rf12()
        xd = tower.fp12_to_device(x)
        out = jax.jit(lambda f: dpair.final_exponentiation(f, False))(xd)
        assert tower.fp12_from_device(out) == \
            hp.final_exponentiation(x, canonical=False)

    def test_bls_verify_good_and_bad(self, verify_compiled):
        fn, pub_d, sig_d = verify_compiled
        msg_d = dpair.g2_affine_to_device(hash_to_g2(b"seed"))[None]
        assert bool(fn(pub_d, sig_d, msg_d)[0])
        bad_d = dpair.g2_affine_to_device(hash_to_g2(b"seed").mul(43))[None]
        assert not bool(fn(pub_d, bad_d, msg_d)[0])

    def test_bls_verify_batch(self, verify_compiled):
        """Batch axis: one good, one corrupted — elementwise verdicts."""
        fn, pub_d, _ = verify_compiled
        good = hash_to_g2(b"seed").mul(42)
        bad = hash_to_g2(b"seed").mul(99)
        sigs = jnp.stack([dpair.g2_affine_to_device(good),
                          dpair.g2_affine_to_device(bad)])  # (2, 2, 2, 32)
        msg = dpair.g2_affine_to_device(hash_to_g2(b"seed"))
        msgs = jnp.broadcast_to(msg, (2, 2, 2, 32))
        out = fn(pub_d, sigs, msgs)
        assert out.shape == (2,)
        assert bool(out[0]) and not bool(out[1])
