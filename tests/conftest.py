"""Test configuration.

Sharding/multi-chip tests run on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multi-chip path; real TPU hardware has one
chip under axon). Set up the XLA flags BEFORE jax is imported anywhere.

NB: under the axon image a sitecustomize imports jax at interpreter boot
and registers the tunneled TPU backend — the JAX_PLATFORMS env var is
read too early to override it, which used to make an innocent
``pytest tests/`` run every graph against the (slow, possibly down)
tunnel. ``jax.config.update("jax_platforms", "cpu")`` DOES override it
post-import (the backend itself initializes lazily), so the suite pins
the CPU mesh programmatically and the documented fast path
(`-m "not device and not slow"`, <5 min) works for a cold user with no
environment knowledge. Set DRAND_TPU_TEST_TPU=1 to deliberately run the
suite against the real device instead.
"""

import asyncio
import inspect
import os
import sys

# XLA_FLAGS must be in place before the (lazy) backend initialization
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
if os.environ.get("DRAND_TPU_TEST_TPU") != "1":
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    # effective even when the axon sitecustomize already imported jax
    # and registered the tunnel backend (env vars alone are not)
    jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from drand_tpu.utils.jit_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import pytest  # noqa: E402


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests via asyncio.run (no pytest-asyncio in the
    image); the inert @pytest.mark.asyncio markers stay readable."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (run by conftest)")
    config.addinivalue_line(
        "markers",
        "device: device-compile-heavy test (multi-minute XLA/Mosaic "
        "compiles on a small host)")
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-heavy protocol test (multi-process e2e, "
        "100-round scale runs)")


# Markers live with the code they describe: device-compile-heavy modules
# (pairing/h2c/MSM graph compiles, minutes each on a 1-core host) carry
# `pytestmark = pytest.mark.device`; multi-process/scale tests carry
# `pytest.mark.slow`. The documented fast path (README) is
# `-m "not device and not slow"` (~3.5 min warm).


def sample_count(registry, fam_name: str, **labels) -> float:
    """Sum of _count/_total samples of a metric family matching the
    given labels — shared by the metrics and tracing suites."""
    total = 0.0
    for fam in registry.collect():
        if fam.name != fam_name:
            continue
        for s in fam.samples:
            if not (s.name.endswith("_count") or s.name.endswith("_total")):
                continue
            if all(s.labels.get(k) == v for k, v in labels.items()):
                total += s.value
    return total
