"""Test configuration.

Sharding/multi-chip tests run on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multi-chip path; real TPU hardware has one
chip under axon). Set up the XLA flags BEFORE jax is imported anywhere.

NB: under the axon image a sitecustomize imports jax at interpreter boot,
so the JAX_PLATFORMS assignment below only takes effect when the suite runs
with a clean PYTHONPATH (PYTHONPATH= JAX_PLATFORMS=cpu python -m pytest …);
under the ambient environment the suite runs against the tunneled TPU chip,
which is also a valid (slower, hardware-exercising) configuration. Tests
that REQUIRE more than one device must check jax.device_count() and skip.
"""

import asyncio
import inspect
import os
import sys

# Force-assign (not setdefault): the ambient shell defaults to
# JAX_PLATFORMS=axon (remote TPU tunnel); the test suite prefers the
# virtual CPU mesh when jax has not been imported yet.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from drand_tpu.utils.jit_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import pytest  # noqa: E402


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    """Run ``async def`` tests via asyncio.run (no pytest-asyncio in the
    image); the inert @pytest.mark.asyncio markers stay readable."""
    fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(fn):
        kwargs = {name: pyfuncitem.funcargs[name]
                  for name in pyfuncitem._fixtureinfo.argnames}
        asyncio.run(fn(**kwargs))
        return True
    return None


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: async test (run by conftest)")
    config.addinivalue_line(
        "markers",
        "device: device-compile-heavy test (multi-minute XLA/Mosaic "
        "compiles on a small host)")
    config.addinivalue_line(
        "markers",
        "slow: wall-clock-heavy protocol test (multi-process e2e, "
        "100-round scale runs)")


# Markers live with the code they describe: device-compile-heavy modules
# (pairing/h2c/MSM graph compiles, minutes each on a 1-core host) carry
# `pytestmark = pytest.mark.device`; multi-process/scale tests carry
# `pytest.mark.slow`. The documented fast path (README) is
# `-m "not device and not slow"` (~3.5 min warm).
