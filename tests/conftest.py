"""Test configuration.

Sharding/multi-chip tests run on a virtual 8-device CPU mesh (the driver
separately dry-run-compiles the multi-chip path; real TPU hardware has one
chip under axon). Set up the XLA flags BEFORE jax is imported anywhere.
"""

import os
import sys

# Force-assign (not setdefault): the ambient shell defaults to
# JAX_PLATFORMS=axon (remote TPU tunnel); the test suite must run on the
# virtual CPU mesh regardless.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from drand_tpu.utils.jit_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
