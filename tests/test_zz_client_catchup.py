"""Million-client catch-up (ISSUE 17): adaptive RLC span walk,
pipelined fetch/verify with cancel-resume trust, the bounded trust
ring, checkpointed bootstrap (daemon recovery + HTTP surface + client
acceptance and forgery rejection), and the cancellation-safe fetch
helper.

Late-alphabet filename per the tier-1 chunking convention. Structural
crypto covers the walk-machinery scenarios; the checkpoint forgery
matrix and the product-check accounting run real pairings on small
chains. Everything is host-only (the autouse fixture pins the batch
dispatch, so no device graphs and no fresh XLA compiles).
"""

import asyncio
import dataclasses

import aiohttp
import pytest
from conftest import sample_count as _sample_count

from drand_tpu import metrics
from drand_tpu.chain.beacon import Beacon, message, verify_beacon
from drand_tpu.chain.info import Info
from drand_tpu.client import checkpoint as ckpt_mod
from drand_tpu.client import verify as verify_mod
from drand_tpu.client.direct import DirectClient
from drand_tpu.client.interface import ClientError, result_from_beacon
from drand_tpu.client.verify import VerifyingClient
from drand_tpu.crypto import batch, bls
from drand_tpu.crypto import pairing as hpairing
from drand_tpu.crypto.curves import PointG1
from drand_tpu.http_server.server import PublicServer
from drand_tpu.net.packets import PartialBeaconPacket
from drand_tpu.net.transport import TransportError
from drand_tpu.testing.chaos import (ChaosBeaconNetwork, group_sig,
                                     structural_crypto)

GENESIS = b"\x42" * 32


@pytest.fixture(autouse=True)
def _host_crypto():
    """Pin the dispatch to host crypto: a stray verify_beacons must not
    kick the jax backend probe mid-test (minute-scale cold compile)."""
    saved = batch._MODE
    batch.configure("host")
    yield
    batch.configure(saved)


def build_chain(n, genesis=GENESIS):
    """Structural chain: sig_r = group_sig(message(r, prev))."""
    prev, out = genesis, []
    for r in range(1, n + 1):
        sig = group_sig(message(r, prev))
        out.append(Beacon(round=r, previous_sig=prev, signature=sig))
        prev = sig
    return out


def structural_info():
    return Info(public_key=PointG1.generator(), period=3, genesis_time=0,
                genesis_seed=GENESIS)


class ChainSource:
    """In-memory source over a beacon list. ``span``/``checkpoint``
    toggle the optional surfaces the client probes via getattr."""

    def __init__(self, beacons, info, checkpoint=None, span=True):
        self._b = beacons
        self._info = info
        self._ckpt = checkpoint
        if not span:
            self.get_span = None
        if checkpoint is None:
            self.get_checkpoint = None

    async def info(self):
        return self._info

    async def get(self, rn=0):
        rn = rn or len(self._b)
        if not 1 <= rn <= len(self._b):
            raise ClientError(f"round {rn} not in chain")
        return result_from_beacon(self._b[rn - 1])

    async def get_span(self, lo, hi):
        return self._b[lo - 1:hi - 1]

    async def get_checkpoint(self):
        return self._ckpt


def corrupt(beacons, bad_round):
    """One corrupt signature with SELF-CONSISTENT onward linkage (a
    forging source would serve exactly this), so only the signature
    check — not the cheap linkage scan — can catch it."""
    out = list(beacons)
    bad_sig = bytes(96)
    out[bad_round - 1] = dataclasses.replace(out[bad_round - 1],
                                             signature=bad_sig)
    if bad_round < len(out):
        out[bad_round] = dataclasses.replace(out[bad_round],
                                             previous_sig=bad_sig)
    return out


def counting_verify():
    """Wrap the CURRENT batch.verify_beacons (structural or host) with
    a span-verification counter; returns (counter_dict, restore_fn)."""
    orig = batch.verify_beacons
    n = {"calls": 0}

    def wrapped(pub, beacons, dst=b""):
        n["calls"] += 1
        return orig(pub, beacons)

    batch.verify_beacons = wrapped
    return n, lambda: setattr(batch, "verify_beacons", orig)


# ---------------------------------------------------------------------------
# adaptive chunks + corruption bisection
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_adaptive_chunk_grows_then_shrinks_on_corruption():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(600)
        vc = VerifyingClient(ChainSource(chain, info), strict_rounds=True,
                            use_checkpoints=False)
        await vc.get(600)
        grown = vc._chunk
        assert grown > verify_mod.CATCHUP_CHUNK  # doubled while clean

        # corruption at round 400 lands in the third (256-round) chunk:
        # the bisection names the exact round and the chunk halves
        bad = VerifyingClient(ChainSource(corrupt(chain, 400), info),
                              strict_rounds=True, use_checkpoints=False)
        with pytest.raises(ClientError, match="round 400: invalid"):
            await bad.get(600)
        assert verify_mod.CATCHUP_CHUNK <= bad._chunk < 256


@pytest.mark.asyncio
async def test_broken_linkage_names_round():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(96)
        # linkage break WITHOUT self-consistent onward prev: the cheap
        # scan catches it before any span verification
        chain[40] = dataclasses.replace(chain[40], previous_sig=b"\x13" * 96)
        vc = VerifyingClient(ChainSource(chain, info), strict_rounds=True,
                            use_checkpoints=False)
        with pytest.raises(ClientError, match="round 41: broken signature"):
            await vc.get(96)


# ---------------------------------------------------------------------------
# trust ring: old-round re-fetch without re-walking
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_trust_ring_zero_span_verifications_on_refetch():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(300)
        vc = VerifyingClient(ChainSource(chain, info), strict_rounds=True,
                            use_checkpoints=False)
        await vc.get(300)  # long walk: ring holds the chunk tails
        assert vc._trust[0] == 300

        counter, restore = counting_verify()
        try:
            # round 65's predecessor (64) is a chunk tail in the ring:
            # the re-fetch must not re-verify ANY span
            r = await vc.get(65)
            assert r.round == 65
            assert counter["calls"] == 0
            # a round just past a ring point resumes from it, not
            # genesis: one span of exactly the small gap
            await vc.get(70)
            assert counter["calls"] == 1
        finally:
            restore()


# ---------------------------------------------------------------------------
# pipeline: cancel mid-walk persists per-chunk trust, resume skips it
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_cancel_mid_walk_resumes_from_verified_chunk():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(256)
        gate = asyncio.Event()
        fetched_los = []

        class GatedSource(ChainSource):
            async def get_span(self, lo, hi):
                fetched_los.append(lo)
                if lo > 64:
                    await gate.wait()
                return await super().get_span(lo, hi)

        vc = VerifyingClient(GatedSource(chain, info), strict_rounds=True,
                            use_checkpoints=False)
        task = asyncio.ensure_future(vc.get(256))
        # first chunk [1,65) verifies; the pipelined prefetch of the
        # second chunk blocks on the gate
        for _ in range(200):
            await asyncio.sleep(0.01)
            if vc._trust is not None and vc._trust[0] >= 64:
                break
        task.cancel()
        await asyncio.gather(task, return_exceptions=True)
        assert vc._trust[0] == 64  # per-chunk persistence survived cancel
        assert chain[63].signature == vc._trust[1]

        # resume: the walk starts from the persisted trust point, never
        # re-fetching the verified prefix
        gate.set()
        fetched_los.clear()
        r = await vc.get(256)
        assert r.round == 256
        assert fetched_los and min(fetched_los) == 65


# ---------------------------------------------------------------------------
# cancellation-safe per-round fetch (the _fetch_span task-leak fix)
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_fetch_rounds_cancels_siblings_on_error():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(64)
        state = {"in_flight": 0, "max_in_flight": 0, "started": 0}

        class FailingSource(ChainSource):
            async def get(self, rn=0):
                if rn == 5:
                    raise TransportError("boom")
                state["in_flight"] += 1
                state["started"] += 1
                state["max_in_flight"] = max(state["max_in_flight"],
                                             state["in_flight"])
                try:
                    await asyncio.sleep(0.2)  # slow enough to be caught
                    return await super().get(rn)
                finally:
                    state["in_flight"] -= 1

        src = FailingSource(chain, info, span=False)
        vc = VerifyingClient(src, strict_rounds=True, use_checkpoints=False)
        with pytest.raises(TransportError):
            await vc.get(64)
        # the failure cancelled AND awaited every sibling before
        # propagating: nothing is still running against the source
        assert state["in_flight"] == 0
        started = state["started"]
        await asyncio.sleep(0.05)
        assert state["started"] == started  # no stragglers started later


# ---------------------------------------------------------------------------
# watch(): transport errors drop the round, not the stream
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_watch_survives_transport_error():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(3)
        info_calls = {"n": 0}

        class FlakySource(ChainSource):
            async def info(self):
                info_calls["n"] += 1
                if info_calls["n"] == 2:  # mid-watch, exactly once
                    raise TransportError("transient relay failure")
                return self._info

            async def watch(self):
                for b in self._b:
                    yield result_from_beacon(b)

        vc = VerifyingClient(FlakySource(chain, info), strict_rounds=False,
                            use_checkpoints=False)
        got = [r.round async for r in vc.watch()]
        assert got == [1, 3]  # round 2 dropped, generator survived


# ---------------------------------------------------------------------------
# get_span validation: a lying bulk source cannot slip rounds through
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_get_span_length_and_round_validation():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(32)

        class ShortSource(ChainSource):
            async def get_span(self, lo, hi):
                return self._b[lo - 1:hi - 2]  # one beacon short

        class ShiftedSource(ChainSource):
            async def get_span(self, lo, hi):
                return self._b[lo:hi]  # off-by-one round numbers

        vc = VerifyingClient(ShortSource(chain, info), strict_rounds=True,
                            use_checkpoints=False)
        with pytest.raises(ClientError, match="rounds for span"):
            await vc.get(32)
        vc2 = VerifyingClient(ShiftedSource(chain, info), strict_rounds=True,
                             use_checkpoints=False)
        with pytest.raises(ClientError, match="returned round"):
            await vc2.get(32)


# ---------------------------------------------------------------------------
# checkpoint bootstrap: acceptance, fallback, forgery rejection
# ---------------------------------------------------------------------------

def make_structural_checkpoint(info, chain, round_no):
    sig = chain[round_no - 1].signature
    return ckpt_mod.Checkpoint(
        round=round_no, signature=sig, chain_hash=info.hash(),
        ckpt_sig=group_sig(ckpt_mod.checkpoint_message(
            info.hash(), round_no, sig)))


@pytest.mark.asyncio
async def test_checkpoint_bootstrap_skips_walk():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(2000)
        ckpt = make_structural_checkpoint(info, chain, 1990)
        ok0 = _sample_count(metrics.CLIENT_REGISTRY,
                            "checkpoint_bootstraps", result="ok")

        counter, restore = counting_verify()
        try:
            vc = VerifyingClient(ChainSource(chain, info, checkpoint=ckpt),
                                 strict_rounds=True)
            r = await vc.get(2000)
            boot_calls = counter["calls"]
            counter["calls"] = 0
            full = VerifyingClient(ChainSource(chain, info),
                                   strict_rounds=True, use_checkpoints=False)
            await full.get(2000)
            walk_calls = counter["calls"]
        finally:
            restore()
        assert r.round == 2000 and vc._trust[0] == 2000
        # O(1): one spot-check batch + the [1991, 2000) tail span — the
        # full walk's span count scales with the chain instead
        assert boot_calls <= 2 < walk_calls
        assert _sample_count(metrics.CLIENT_REGISTRY,
                             "checkpoint_bootstraps",
                             result="ok") == ok0 + 1


@pytest.mark.asyncio
async def test_forged_checkpoint_falls_back_to_full_walk():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(400)
        good = make_structural_checkpoint(info, chain, 390)
        forged = dataclasses.replace(good, ckpt_sig=b"\x66" * 96)
        rej0 = _sample_count(metrics.CLIENT_REGISTRY,
                             "checkpoint_bootstraps", result="rejected")
        vc = VerifyingClient(ChainSource(chain, info, checkpoint=forged),
                             strict_rounds=True)
        r = await vc.get(400)  # rejected checkpoint NEVER blocks the walk
        assert r.round == 400
        assert _sample_count(metrics.CLIENT_REGISTRY,
                             "checkpoint_bootstraps",
                             result="rejected") == rej0 + 1


@pytest.mark.asyncio
async def test_checkpoint_spot_check_catches_corrupt_history():
    with structural_crypto():
        info = structural_info()
        chain = build_chain(400)
        ckpt = make_structural_checkpoint(info, chain, 390)
        # every skipped round is corrupt (self-consistent linkage), so
        # ANY spot-check sample must trip; a valid checkpoint over a
        # corrupt prefix cannot silently launder history
        bad = list(chain)
        for rn in range(2, 389):
            bad = corrupt(bad, rn)
        vc = VerifyingClient(ChainSource(bad, info, checkpoint=ckpt),
                             strict_rounds=True)
        with pytest.raises(ClientError, match="checkpoint spot-check"):
            await vc.get(400)


def test_checkpoint_forgery_matrix_real_crypto():
    """Wrong key, wrong chain hash, tampered round: each forged
    checkpoint is rejected by the real pairing check."""
    sk, pub = bls.keygen(seed=b"ckpt-forgery-test")
    sk2, _pub2 = bls.keygen(seed=b"ckpt-forgery-other")
    info = Info(public_key=pub, period=3, genesis_time=0,
                genesis_seed=GENESIS)
    chain_hash = info.hash()
    sig = b"\x17" * 96  # the attested head signature (opaque here)
    good = ckpt_mod.Checkpoint(
        round=40, signature=sig, chain_hash=chain_hash,
        ckpt_sig=bls.sign(sk, ckpt_mod.checkpoint_message(
            chain_hash, 40, sig)))
    assert ckpt_mod.verify_checkpoint(pub, chain_hash, good)

    wrong_key = dataclasses.replace(good, ckpt_sig=bls.sign(
        sk2, ckpt_mod.checkpoint_message(chain_hash, 40, sig)))
    assert not ckpt_mod.verify_checkpoint(pub, chain_hash, wrong_key)

    other_hash = b"\x99" * 32
    wrong_chain = ckpt_mod.Checkpoint(
        round=40, signature=sig, chain_hash=other_hash,
        ckpt_sig=bls.sign(sk, ckpt_mod.checkpoint_message(
            other_hash, 40, sig)))
    assert not ckpt_mod.verify_checkpoint(pub, chain_hash, wrong_chain)

    tampered_round = dataclasses.replace(good, round=41)
    assert not ckpt_mod.verify_checkpoint(pub, chain_hash, tampered_round)

    # malformed-JSON surface of the same trust boundary
    with pytest.raises(ClientError, match="malformed checkpoint"):
        ckpt_mod.checkpoint_from_json({"round": "x"})
    assert ckpt_mod.checkpoint_from_json(
        ckpt_mod.checkpoint_json(good)) == good


@pytest.mark.asyncio
async def test_real_bootstrap_constant_product_checks(monkeypatch):
    """N_PRODUCT_CHECKS accounting on a real-crypto chain: the
    checkpoint bootstrap spends a CONSTANT number of product checks
    (checkpoint + spot-check batch + tail span + head), below the full
    walk's chain-scaled span count. The structural test above and the
    client_catchup bench assert the asymptotic separation."""
    monkeypatch.setattr(verify_mod, "CATCHUP_CHUNK", 4)
    monkeypatch.setattr(ckpt_mod, "SPOT_CHECKS", 4)
    sk, pub = bls.keygen(seed=b"ckpt-bootstrap-test")
    info = Info(public_key=pub, period=3, genesis_time=0,
                genesis_seed=GENESIS)
    prev, chain = GENESIS, []
    for rnd in range(1, 41):
        sig = bls.sign(sk, message(rnd, prev))
        chain.append(Beacon(round=rnd, previous_sig=prev, signature=sig))
        prev = sig
    ckpt = ckpt_mod.Checkpoint(
        round=36, signature=chain[35].signature, chain_hash=info.hash(),
        ckpt_sig=bls.sign(sk, ckpt_mod.checkpoint_message(
            info.hash(), 36, chain[35].signature)))

    c0 = hpairing.N_PRODUCT_CHECKS
    vc = VerifyingClient(ChainSource(chain, info, checkpoint=ckpt),
                         strict_rounds=True)
    assert (await vc.get(40)).round == 40
    boot_checks = hpairing.N_PRODUCT_CHECKS - c0

    c0 = hpairing.N_PRODUCT_CHECKS
    full = VerifyingClient(ChainSource(chain, info), strict_rounds=True,
                           use_checkpoints=False)
    assert (await full.get(40)).round == 40
    walk_checks = hpairing.N_PRODUCT_CHECKS - c0
    assert boot_checks <= 4 < walk_checks


# ---------------------------------------------------------------------------
# daemon recovery + HTTP surface + wire plumbing
# ---------------------------------------------------------------------------

@pytest.mark.asyncio
async def test_daemon_recovers_checkpoint_and_serves_it():
    with structural_crypto():
        net = ChaosBeaconNetwork(n=3, t=2, period=4)
        for h in net.handlers:
            h._ckpt_interval = 2
        await net.start_all()
        await net.advance_to_genesis()
        server = PublicServer(DirectClient(net.handlers[0]),
                              clock=net.clocks[0])
        site = await server.start("127.0.0.1", 0)
        port = site._server.sockets[0].getsockname()[1]
        url = f"http://127.0.0.1:{port}/checkpoints/latest"
        try:
            async with aiohttp.ClientSession() as sess:
                async with sess.get(url) as resp:
                    assert resp.status == 404  # nothing recovered yet
                for _ in range(4):
                    await net.advance_round()
                ckpt = net.handlers[0].checkpoint()
                assert ckpt is not None and ckpt.round % 2 == 0
                async with sess.get(url) as resp:
                    assert resp.status == 200
                    body = await resp.json()
            got = ckpt_mod.checkpoint_from_json(body)
            assert got == ckpt
            info = net.handlers[0].crypto.chain_info
            assert ckpt_mod.verify_checkpoint(info.public_key, info.hash(),
                                              got)
            # the issued-checkpoint telemetry moved with the recovery
            assert metrics.CKPT_ROUND._value.get() == ckpt.round
        finally:
            await server.stop()
            net.stop_all()


def test_partial_ckpt_wire_roundtrip():
    from drand_tpu.net import protowire, wire

    p = PartialBeaconPacket(round=9, previous_sig=b"\x01" * 96,
                            partial_sig=b"\x02" * 98, partial_sig_v2=b"",
                            partial_ckpt=b"\x03" * 98)
    obj, _addr = wire.decode(wire.encode(p, from_addr="a.test:1"))
    assert obj == p
    raw = protowire.encode(protowire.PARTIAL_BEACON_PACKET,
                           dataclasses.asdict(p))
    back = protowire.decode(protowire.PARTIAL_BEACON_PACKET, raw)
    assert back["partial_ckpt"] == p.partial_ckpt

    # decode fills the default for packets from pre-checkpoint peers
    old = PartialBeaconPacket(round=9, previous_sig=b"\x01" * 96,
                              partial_sig=b"\x02" * 98, partial_sig_v2=b"")
    raw_old = protowire.encode(protowire.PARTIAL_BEACON_PACKET,
                               dataclasses.asdict(old))
    assert protowire.decode(protowire.PARTIAL_BEACON_PACKET,
                            raw_old)["partial_ckpt"] == b""
