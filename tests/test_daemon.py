"""Daemon-level integration: real DKG over the transport, beacon rounds,
resharing with transition, and restart-from-disk.

Reference coverage model: core/drand_test.go (TestRunDKG :40,
TestRunDKGReshare :182, TestDrandPublicChainInfo via harness) driven by the
DrandTest2 rig (core/util_test.go:32) — here over LocalNetwork with a fake
clock, through the real control-plane entry points (init_dkg_leader/
init_dkg_follower/init_reshare_*), with NO synthesize_shares anywhere.
"""

import asyncio

import pytest

from drand_tpu.chain.beacon import verify_beacon, verify_beacon_v2
from drand_tpu.core.config import Config
from drand_tpu.core.daemon import Drand
from drand_tpu.key.store import FileStore
from drand_tpu.net.transport import LocalNetwork
from drand_tpu.utils.clock import FakeClock

SECRET = b"setup-secret-0123456789abcdef"
PERIOD = 5


def make_daemon(i, net, clock, tmp_path, db=False):
    addr = f"d{i}.test:70{i:02d}"
    ks = FileStore(str(tmp_path / f"node{i}"))
    conf = Config(clock=clock, dkg_timeout=10,
                  db_path=str(tmp_path / f"node{i}" / "chain.db") if db else "")
    d = Drand.fresh(ks, conf, net.client_for(addr), addr)
    net.register(addr, d)
    return addr, ks, conf, d


async def wait_chain(daemon, round_no, timeout=30.0):
    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        try:
            if daemon.beacon is not None and \
                    daemon.beacon.chain.last().round >= round_no:
                return
        except Exception:
            pass
        if asyncio.get_event_loop().time() > deadline:
            raise TimeoutError(f"{daemon.priv.public.addr} stuck at "
                               f"{daemon.beacon.chain.last().round}")
        await asyncio.sleep(0.01)


async def form_network(n, t, net, clock, tmp_path, db=False):
    daemons = []
    leader_addr = None
    for i in range(n):
        addr, *_, d = make_daemon(i, net, clock, tmp_path, db=db)
        leader_addr = leader_addr or addr
        daemons.append(d)
    tasks = [asyncio.ensure_future(
        daemons[0].init_dkg_leader(n, t, PERIOD, SECRET, timeout=20))]
    for d in daemons[1:]:
        tasks.append(asyncio.ensure_future(
            d.init_dkg_follower(leader_addr, SECRET, timeout=20)))
    groups = await asyncio.gather(*tasks)
    assert all(g.hash() == groups[0].hash() for g in groups)
    return daemons, groups[0]


@pytest.mark.asyncio
async def test_daemon_dkg_to_beacon(tmp_path):
    clock = FakeClock()
    net = LocalNetwork()
    daemons, group = await form_network(3, 2, net, clock, tmp_path)
    assert group.public_key is not None
    await clock.advance_to(group.genesis_time)
    for _ in range(3):
        await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 3)
        pub = group.public_key.key()
        for r in range(1, 4):
            b = d.beacon.chain.get(r)
            assert verify_beacon(pub, b)
            assert b.is_v2() and verify_beacon_v2(pub, b)
    for d in daemons:
        d.stop()


@pytest.mark.asyncio
async def test_daemon_restart_from_disk(tmp_path):
    """Kill a node, reload it from its key store + chain db, catch up."""
    clock = FakeClock()
    net = LocalNetwork()
    daemons, group = await form_network(3, 2, net, clock, tmp_path, db=True)
    await clock.advance_to(group.genesis_time)
    for _ in range(2):
        await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 2)

    # kill node 2: unregister + stop
    victim = daemons[2]
    addr2 = victim.priv.public.addr
    victim.stop()
    net.unregister(addr2)
    for _ in range(3):
        await clock.advance(PERIOD)
    for d in daemons[:2]:
        await wait_chain(d, 5)

    # reload from disk: identity, group, share and chain all persisted
    ks = FileStore(str(tmp_path / "node2"))
    conf = Config(clock=clock, dkg_timeout=10,
                  db_path=str(tmp_path / "node2" / "chain.db"))
    revived = Drand.load(ks, conf, net.client_for(addr2))
    assert revived.group is not None and revived.share is not None
    assert revived.group.hash() == group.hash()
    assert revived.share.pri_share == victim.share.pri_share
    net.register(addr2, revived)
    revived.start_beacon(catchup=True)
    await asyncio.sleep(0.05)  # let catchup sync run
    await wait_chain(revived, 5)
    await clock.advance(PERIOD)
    for d in daemons[:2] + [revived]:
        await wait_chain(d, 6)
    for d in daemons[:2] + [revived]:
        d.stop()


@pytest.mark.asyncio
async def test_daemon_reshare_grows_group(tmp_path):
    """3-of-2 network reshares to 4 nodes (threshold 3): the chain identity
    and distributed key survive, the new node serves rounds after T."""
    clock = FakeClock()
    net = LocalNetwork()
    daemons, group = await form_network(3, 2, net, clock, tmp_path)
    await clock.advance_to(group.genesis_time)
    for _ in range(2):
        await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 2)

    # add node 3 (fresh keypair, knows the old group file out of band)
    addr3, ks3, conf3, joiner = make_daemon(3, net, clock, tmp_path)
    leader_addr = daemons[0].priv.public.addr
    reshare_secret = b"reshare-secret-aaaaaaaaaaaaaaaa"
    tasks = [asyncio.ensure_future(
        daemons[0].init_reshare_leader(4, 3, reshare_secret, timeout=20))]
    for d in daemons[1:]:
        tasks.append(asyncio.ensure_future(
            d.init_reshare_follower(leader_addr, reshare_secret, timeout=20)))
    tasks.append(asyncio.ensure_future(
        joiner.init_reshare_follower(leader_addr, reshare_secret,
                                     old_group=group, timeout=20)))
    new_groups = await asyncio.gather(*tasks)
    new_group = new_groups[0]
    assert all(g.hash() == new_group.hash() for g in new_groups)
    # chain identity preserved
    assert new_group.genesis_seed == group.genesis_seed
    assert new_group.public_key.key() == group.public_key.key()
    assert len(new_group) == 4 and new_group.threshold == 3

    # cross the transition boundary and keep producing
    await clock.advance_to(new_group.transition_time)
    for _ in range(3):
        await clock.advance(PERIOD)
    t_round = group.current_round(new_group.transition_time)
    target = t_round + 2
    for d in daemons + [joiner]:
        await wait_chain(d, target)
        pub = new_group.public_key.key()
        b = d.beacon.chain.get(target)
        assert verify_beacon(pub, b)
    for d in daemons + [joiner]:
        d.stop()


@pytest.mark.asyncio
async def test_reshare_timeout_aborts_and_retry_succeeds(tmp_path):
    """Adversarial reshare path (core/drand_test.go:261 timeout case): a
    reshare whose participants never show up times out WITHOUT disturbing
    the running chain, and a subsequent reshare attempt succeeds."""
    clock = FakeClock()
    net = LocalNetwork()
    daemons, group = await form_network(2, 2, net, clock, tmp_path)
    await clock.advance_to(group.genesis_time)
    await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 1)

    reshare_secret = b"reshare-secret-aaaaaaaaaaaaaaaa"
    # expected_n=3 but nobody else signals: leader setup must time out
    with pytest.raises(TimeoutError, match="participants signalled"):
        await daemons[0].init_reshare_leader(3, 2, reshare_secret,
                                             timeout=0.5)
    assert daemons[0]._setup_mgr is None, "failed setup not cleaned up"

    # chain still alive on the OLD group
    await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 2)
        assert verify_beacon(group.public_key.key(), d.beacon.chain.get(2))

    # retry with the full membership: succeeds and transitions
    tasks = [asyncio.ensure_future(
        daemons[0].init_reshare_leader(2, 2, reshare_secret, timeout=20))]
    tasks.append(asyncio.ensure_future(
        daemons[1].init_reshare_follower(daemons[0].priv.public.addr,
                                         reshare_secret, timeout=20)))
    new_groups = await asyncio.gather(*tasks)
    assert new_groups[0].hash() == new_groups[1].hash()
    assert new_groups[0].public_key.key() == group.public_key.key()
    for d in daemons:
        d.stop()


@pytest.mark.asyncio
async def test_second_setup_rejected_unless_forced(tmp_path):
    """Preemption guard (core/drand_test.go:182 preempt case +
    drand_control.go force flag): a second concurrent setup errors
    without force; with force it cancels the pending one."""
    from drand_tpu.core.daemon import DrandError
    from drand_tpu.core.setup import SetupPreempted

    clock = FakeClock()
    net = LocalNetwork()
    daemons, group = await form_network(2, 2, net, clock, tmp_path)
    await clock.advance_to(group.genesis_time)
    await clock.advance(PERIOD)
    for d in daemons:
        await wait_chain(d, 1)

    reshare_secret = b"reshare-secret-aaaaaaaaaaaaaaaa"
    # first reshare waits for a third participant that never comes
    first = asyncio.ensure_future(
        daemons[0].init_reshare_leader(3, 2, reshare_secret, timeout=30))
    await asyncio.sleep(0.05)
    assert daemons[0]._setup_mgr is not None

    # un-forced second setup is rejected while the first is pending
    with pytest.raises(DrandError, match="already in progress"):
        await daemons[0].init_reshare_leader(2, 2, reshare_secret,
                                             timeout=5)
    assert not first.done()

    # forced second setup preempts the first and completes
    second = asyncio.ensure_future(
        daemons[0].init_reshare_leader(2, 2, reshare_secret, timeout=20,
                                       force=True))
    follower = asyncio.ensure_future(
        daemons[1].init_reshare_follower(daemons[0].priv.public.addr,
                                         reshare_secret, timeout=20))
    with pytest.raises(SetupPreempted):
        await first
    new_groups = await asyncio.gather(second, follower)
    assert new_groups[0].hash() == new_groups[1].hash()
    assert new_groups[0].public_key.key() == group.public_key.key()
    for d in daemons:
        d.stop()


@pytest.mark.asyncio
async def test_force_preempts_follower_awaiting_group(tmp_path):
    """ADVICE r5: a forced second init while a FOLLOWER setup is still
    awaiting the leader's group packet must cancel that wait (no
    SetupManager exists on the follower side) instead of raising 'the
    DKG phase is already running' — no DKG is running yet."""
    from drand_tpu.core.daemon import DrandError

    clock = FakeClock()
    net = LocalNetwork()
    lead_addr, *_, d_lead = make_daemon(0, net, clock, tmp_path)
    _, *_, d_fol = make_daemon(1, net, clock, tmp_path)

    # leader collects 3 participants (never completes) so the follower's
    # signal is accepted and it parks awaiting the group push
    lead_task = asyncio.ensure_future(
        d_lead.init_dkg_leader(3, 2, PERIOD, SECRET, timeout=30))
    await asyncio.sleep(0.05)
    first = asyncio.ensure_future(
        d_fol.init_dkg_follower(lead_addr, SECRET, timeout=30))
    await asyncio.sleep(0.05)
    assert d_fol._group_packet is not None
    assert not d_fol._group_packet.done()
    assert d_fol._setup_mgr is None  # follower setups have no manager

    # un-forced second init is still rejected
    with pytest.raises(DrandError, match="already in progress"):
        await d_fol.init_dkg_follower(lead_addr, SECRET, timeout=5)
    assert not first.done()

    # forced second init preempts the parked follower: the first init
    # unwinds via the cancelled group-packet future, the second owns the
    # setup slot and parks awaiting a (new) group push
    second = asyncio.ensure_future(
        d_fol.init_dkg_follower(lead_addr, SECRET, timeout=30, force=True))
    with pytest.raises(asyncio.CancelledError):
        await first
    await asyncio.sleep(0.05)
    assert not second.done()
    assert d_fol._group_packet is not None
    assert not d_fol._group_packet.done()

    for t in (second, lead_task):
        t.cancel()
    await asyncio.gather(second, lead_task, return_exceptions=True)
