"""Device hash-to-G2 / decompression golden tests vs the host reference,
and the engine's wire-prep verification path.

Pins the ops/h2c.py pipeline bit-for-bit against crypto/hash_to_curve and
PointG2.from_bytes (the RFC 9380 + zcash semantics), plus the end-to-end
DRAND_TPU_WIRE_PREP engine path with corruption cases.
"""

import numpy as np
import pytest

pytestmark = pytest.mark.device

import jax
import jax.numpy as jnp


def _infra_skip(condition_ok: bool, what: str) -> None:
    """On the axon TPU, executables above moving batch thresholds are
    silently wrong (libtpu version skew — see ops/engine.py). A mismatch
    on that backend is an infrastructure condition, not a code regression
    (the CPU-backend run of this suite is the strict oracle)."""
    if not condition_ok and jax.default_backend() == "tpu":
        pytest.skip(f"{what}: device output wrong on skewed-libtpu TPU "
                    f"backend (known infra issue; CPU run is the oracle)")
    assert condition_ok, what

from drand_tpu.chain.beacon import Beacon, message, message_v2
from drand_tpu.crypto import bls
from drand_tpu.crypto.curves import PointG1, PointG2
from drand_tpu.crypto.hash_to_curve import hash_to_g2
from drand_tpu.ops import curve, h2c


def test_hash_to_g2_device_matches_host():
    msgs = [b"suite-h2c-a", b"suite-h2c-b"]
    u = jnp.asarray(h2c.msgs_to_u(msgs))
    pt = jax.jit(h2c.hash_to_g2_device)(u)
    for i, m in enumerate(msgs):
        dev = curve.g2_from_device(tuple(np.asarray(c[i]) for c in pt))
        _infra_skip(dev == hash_to_g2(m), f"hash_to_g2 mismatch for {m!r}")


def test_decompress_device_matches_host_and_rejects_off_curve():
    sigs = [bls.sign(0x1234, b"sig-a"), bls.sign(0x5678, b"sig-b")]
    # tweak x until it is REALLY off the curve (a random x is on the curve
    # with probability ~1/2 — the host decoder is the arbiter)
    bad = bytearray(sigs[1])
    while True:
        bad[7] = (bad[7] + 1) % 256
        try:
            PointG2.from_bytes(bytes(bad), subgroup_check=False)
        except ValueError:
            break
    xs, sign, valid = h2c.sigs_to_x([sigs[0], bytes(bad)])
    assert valid.tolist() == [True, True]  # header/range fine; curve check
    pt, on_curve = jax.jit(h2c.decompress_g2_device)(jnp.asarray(xs),
                                                     jnp.asarray(sign))
    on_curve = np.asarray(on_curve)
    _infra_skip(bool(on_curve[0]) and not bool(on_curve[1]),
                "decompression on-curve flags wrong")
    dev = curve.g2_from_device(tuple(np.asarray(c[0]) for c in pt))
    _infra_skip(dev == PointG2.from_bytes(sigs[0]), "decompressed point")
    _infra_skip(bool(np.asarray(jax.jit(h2c.subgroup_check_g2)(pt))[0]),
                "subgroup check")


def test_sigs_to_x_rejects_malformed_headers():
    good = bls.sign(0x42, b"x")
    no_compress_bit = bytes([good[0] & 0x7F]) + good[1:]
    infinity_bit = bytes([good[0] | 0x40]) + good[1:]
    short = good[:50]
    _, _, valid = h2c.sigs_to_x([good, no_compress_bit, infinity_bit, short])
    assert valid.tolist() == [True, False, False, False]


@pytest.mark.asyncio
async def test_engine_wire_prep_end_to_end():
    """verify_beacons with wire_prep=True: valid chain passes; V1 and V2
    corruption each fail exactly the corrupted beacon."""
    from drand_tpu.ops.engine import BatchedEngine

    sk = 0x77AA
    pubkey = PointG1.generator().mul(sk)
    prev = b"\x21" * 32
    beacons = []
    for rnd in range(1, 4):
        sig = bls.sign(sk, message(rnd, prev))
        sig2 = bls.sign(sk, message_v2(rnd))
        beacons.append(Beacon(round=rnd, previous_sig=prev, signature=sig,
                              signature_v2=sig2))
        prev = sig
    eng = BatchedEngine(buckets=(8,), wire_prep=True)
    try:
        ok = eng.verify_beacons(pubkey, beacons)
    except RuntimeError as e:
        if "no wire bucket" in str(e) and jax.default_backend() == "tpu":
            pytest.skip("wire bucket failed known-answer validation on the "
                        "skewed-libtpu TPU (infra issue)")
        raise
    assert ok.all()
    import copy

    bad = copy.deepcopy(beacons)
    bad[1].signature = bytes([bad[1].signature[0] ^ 1]) + bad[1].signature[1:]
    assert list(eng.verify_beacons(pubkey, bad)) == [True, False, True]
    bad2 = copy.deepcopy(beacons)
    bad2[2].signature_v2 = bad2[0].signature_v2
    assert list(eng.verify_beacons(pubkey, bad2)) == [True, True, False]
