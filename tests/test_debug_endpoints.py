"""Opt-in debug/profiling endpoints (metrics/pprof/pprof.go analogue)."""

import aiohttp
import pytest

from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.testing.harness import BeaconTestNetwork


@pytest.mark.asyncio
async def test_debug_routes_opt_in():
    net = BeaconTestNetwork(n=3, t=2, period=5)
    await net.start_all()
    await net.advance_to_genesis()
    await net.clock.advance(5)
    await net.wait_round(0, 1)
    on = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock,
                      enable_pprof=True)
    off = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock)
    site_on = await on.start("127.0.0.1", 0)
    site_off = await off.start("127.0.0.1", 0)
    p_on = site_on._server.sockets[0].getsockname()[1]
    p_off = site_off._server.sockets[0].getsockname()[1]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{p_on}/debug/gc") as r:
                assert r.status == 200
                assert "collected" in await r.json()
            async with s.get(f"http://127.0.0.1:{p_on}"
                             f"/debug/pprof/stacks") as r:
                assert r.status == 200
                assert "thread" in await r.text()
            async with s.get(f"http://127.0.0.1:{p_on}"
                             f"/debug/pprof/profile?seconds=0.2") as r:
                assert r.status == 200
                assert "cumulative" in await r.text()
            # debug surface is OFF by default
            async with s.get(f"http://127.0.0.1:{p_off}/debug/gc") as r:
                assert r.status == 404
    finally:
        await on.stop()
        await off.stop()
        net.stop_all()
