"""Opt-in debug/profiling endpoints (metrics/pprof/pprof.go analogue)
and the always-on /debug/trace round-timeline surface (obs/trace.py)."""

import logging

import aiohttp
import pytest

from drand_tpu.client.direct import DirectClient
from drand_tpu.http_server.server import PublicServer
from drand_tpu.obs import trace
from drand_tpu.obs.state import reset_observability
from drand_tpu.testing.harness import BeaconTestNetwork


@pytest.mark.asyncio
async def test_debug_routes_opt_in():
    net = BeaconTestNetwork(n=3, t=2, period=5)
    await net.start_all()
    await net.advance_to_genesis()
    await net.clock.advance(5)
    await net.wait_round(0, 1)
    on = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock,
                      enable_pprof=True)
    off = PublicServer(DirectClient(net.nodes[0].handler), clock=net.clock)
    site_on = await on.start("127.0.0.1", 0)
    site_off = await off.start("127.0.0.1", 0)
    p_on = site_on._server.sockets[0].getsockname()[1]
    p_off = site_off._server.sockets[0].getsockname()[1]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{p_on}/debug/gc") as r:
                assert r.status == 200
                assert "collected" in await r.json()
            async with s.get(f"http://127.0.0.1:{p_on}"
                             f"/debug/pprof/stacks") as r:
                assert r.status == 200
                assert "thread" in await r.text()
            async with s.get(f"http://127.0.0.1:{p_on}"
                             f"/debug/pprof/profile?seconds=0.2") as r:
                assert r.status == 200
                assert "cumulative" in await r.text()
            # debug surface is OFF by default
            async with s.get(f"http://127.0.0.1:{p_off}/debug/gc") as r:
                assert r.status == 404
    finally:
        await on.stop()
        await off.stop()
        net.stop_all()


def _capture_harness_logs(caplog):
    """The harness logs at level 'none'; raise every already-created
    beacon-test logger to INFO so caplog sees the aggregator lines."""
    for name in list(logging.root.manager.loggerDict):
        if name.startswith("beacon-test"):
            logging.getLogger(name).setLevel(logging.INFO)
    caplog.set_level(logging.INFO)


@pytest.mark.asyncio
async def test_trace_rounds_timeline(caplog):
    """ISSUE 1 acceptance: a harness round yields a /debug/trace/rounds
    timeline with the named pipeline stages, on the SAME deterministic
    trace id every node derives, and that id shows up in the KV logs."""
    reset_observability()
    net = BeaconTestNetwork(n=3, t=2, period=5)
    _capture_harness_logs(caplog)
    await net.start_all()
    await net.advance_to_genesis()
    await net.clock.advance(5)
    await net.wait_round(0, 1)
    # /debug/trace is always on — no enable_pprof needed
    server = PublicServer(DirectClient(net.nodes[0].handler),
                         clock=net.clock)
    site = await server.start("127.0.0.1", 0)
    port = site._server.sockets[0].getsockname()[1]
    try:
        async with aiohttp.ClientSession() as s:
            async with s.get(f"http://127.0.0.1:{port}"
                             f"/debug/trace/rounds?n=4") as r:
                assert r.status == 200
                data = await r.json()
            async with s.get(f"http://127.0.0.1:{port}"
                             f"/debug/trace/rounds?n=zzz") as r:
                assert r.status == 400
            # the beacon response carries the round-correlation header
            async with s.get(f"http://127.0.0.1:{port}/public/1") as r:
                assert r.status == 200
                parsed = trace.parse_traceparent(
                    r.headers.get(trace.TRACEPARENT_HEADER))
    finally:
        await server.stop()
        net.stop_all()

    seed = net.group.get_genesis_seed()
    by_round = {rec["round"]: rec for rec in data["rounds"]}
    assert 1 in by_round
    rec = by_round[1]
    # all nodes derive the same id: the ring stitched their spans into
    # one timeline keyed by round_trace_id(round, chain)
    tid = trace.round_trace_id(1, seed)
    assert rec["trace_id"] == tid
    assert parsed is not None and parsed[0] == tid
    stages = {sp["name"] for sp in rec["spans"]}
    assert {"partial", "partial_verify", "collect",
            "recover", "verify", "store"} <= stages
    # spans carry real timing
    assert all(sp["duration_ms"] is not None for sp in rec["spans"])
    # the same correlation key appears on the aggregator's log lines
    assert any(f"trace={tid}" in m for m in caplog.messages)
