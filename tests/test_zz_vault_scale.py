"""Planet-scale timelock vault tier (ISSUE 20): segment backend, CLI
migration, bounded chunked opens, partitioned sweeps, open-notify.

Late-alphabet filename per the tier-1 chunking convention
(tools/tier1_chunks.sh). Everything here is host-only — an autouse
fixture pins the batch dispatcher to host crypto, and real pairings run
only on handfuls of ciphertexts. The migration test spawns the CLI as a
subprocess (the chaos/fanout worker-smoke pattern).

Covers: the token-shard math tiling [0, 2^256) exactly, SQLite<->segment
migration equivalence BOTH directions through `util store-migrate
--vault`, O(1)-at-depth status/pending_count on the segment backend,
crash-mid-sweep resume opening every remaining ciphertext exactly once,
a two-worker partitioned sweep over one shared vault directory, the SSE
open-notify leg (delivery, decided-snapshot, firehose, shedding), and
immutability + restart persistence on the segment backend.
"""

from __future__ import annotations

import asyncio
import base64
import json
import os
import subprocess
import sys
import time

import pytest

from conftest import sample_count
from drand_tpu import metrics
from drand_tpu.chain.beacon import message, message_v2
from drand_tpu.chain.info import Info
from drand_tpu.client import timelock as client_timelock
from drand_tpu.client.interface import Client, ClientError, Result
from drand_tpu.crypto import batch, bls
from drand_tpu.crypto import timelock as tl
from drand_tpu.http_server import fanout
from drand_tpu.timelock import segvault
from drand_tpu.timelock.segvault import (SHARD_SPACE_BITS, SegmentVault,
                                         open_vault, shard_bounds,
                                         shard_hex_bounds, token_in_shard)
from drand_tpu.timelock.vault import TimelockVault, VaultError

SK, PUB = bls.keygen(seed=b"zz-vault-scale-tests")
INFO = Info(public_key=PUB, period=3, genesis_time=1_700_000_000,
            genesis_seed=b"\x07" * 32)
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _result(rd: int) -> Result:
    return Result(round=rd, signature=bls.sign(SK, message(rd, b"prev")),
                  signature_v2=bls.sign(SK, message_v2(rd)))


def _tok(i: int) -> str:
    """Deterministic well-distributed 32-hex tokens (the blake2b token
    shape — NOT format(i, '032x'), whose shared zero prefix would pile
    every row into one hash-table neighborhood)."""
    import hashlib

    return hashlib.blake2b(i.to_bytes(8, "big"),
                           digest_size=16).hexdigest()


def _row(i: int, round_no: int = 5, status: str = "pending") -> dict:
    return {"id": _tok(i), "round": round_no,
            "envelope": json.dumps({"U": "aa", "V": "bb",
                                    "round": round_no, "n": i},
                                   sort_keys=True),
            "status": status,
            "plaintext": b"pt-%d" % i if status == "opened" else None,
            "error": "bad pairing" if status == "rejected" else None,
            "submitted": 1000.0 + i,
            "opened": 2000.0 + i if status != "pending" else None}


@pytest.fixture(autouse=True)
def host_mode():
    """Pin the dispatcher to host crypto for every test here (a vault
    test must not probe or compile a device engine)."""
    old = (batch._MODE, batch._MIN_BATCH, batch._ENGINE)
    batch.configure("host")
    yield
    batch._MODE, batch._MIN_BATCH, batch._ENGINE = old


class FakeChain(Client):
    """Hand-advanced chain for service tests."""

    def __init__(self, head: int = 1):
        self.head = head

    async def get(self, round_no: int = 0) -> Result:
        rd = self.head if round_no == 0 else round_no
        if rd > self.head:
            raise ClientError(f"round {rd} not yet produced")
        return _result(rd)

    async def info(self) -> Info:
        return INFO


# ----------------------------------------------------------- shard math

def test_shard_math_tiles_token_space_exactly():
    """For every worker count the shards tile [0, 2^256) with no gap
    and no overlap, and every token lands in exactly one shard — the
    no-interleaved-writes invariant for `relay --workers K`."""
    space = 1 << SHARD_SPACE_BITS
    for count in (1, 2, 3, 5, 7, 8, 16, 64, 256):
        prev_hi = 0
        for i in range(count):
            lo, hi = shard_bounds(i, count)
            assert lo == prev_hi, (count, i)
            assert hi > lo, (count, i)
            prev_hi = hi
        assert prev_hi == space, count
    # hex projection: ascending boundaries, top shard open-ended
    for count in (2, 3, 7, 100):
        bounds = [shard_hex_bounds(i, count) for i in range(count)]
        assert bounds[0][0] == "0" * 32
        assert bounds[-1][1] is None
        for (_, hi), (lo, _) in zip(bounds, bounds[1:]):
            assert hi == lo
    # membership: each sampled token in exactly one shard, agreeing
    # with the hex filter the vault's pending_for_round applies
    for count in (2, 3, 7):
        for i in range(64):
            token = _tok(i)
            owners = [s for s in range(count)
                      if token_in_shard(token, s, count)]
            assert len(owners) == 1, (count, token, owners)
            lo_hex, hi_hex = shard_hex_bounds(owners[0], count)
            assert token >= lo_hex
            assert hi_hex is None or token < hi_hex


# ------------------------------------------- segment vault fundamentals

def test_segment_vault_basics_immutability_and_restart(tmp_path):
    path = str(tmp_path / "seg")
    v = SegmentVault(path)
    env = {"U": "aa", "V": "bb", "round": 9}
    t0, t1, t2 = _tok(0), _tok(1), _tok(2)
    assert v.submit(t0, 9, env) is True
    assert v.submit(t0, 9, env) is False  # idempotent resubmission
    assert v.submit(t1, 9, env) is True
    assert v.submit(t2, 11, env) is True
    assert len(v) == 3 and v.pending_count() == 3
    assert v.pending_rounds() == [9, 11]
    assert v.pending_rounds(up_to=9) == [9]
    assert {t for t, _ in v.pending_for_round(9)} == {t0, t1}
    # malformed ids are unknown, not errors (and unsubmittable)
    assert v.get("nope") is None
    with pytest.raises(VaultError):
        v.submit("not-hex", 9, env)
    v.set_opened(t0, b"plain")
    v.set_rejected(t1, "bad pairing")
    rec = v.get(t0)
    assert rec["status"] == "opened" and rec["plaintext"] == b"plain"
    assert rec["envelope"]["round"] == 9
    assert v.get(t1)["error"] == "bad pairing"
    # decided rows are immutable — every transition re-attempt fails
    for fn in (lambda: v.set_opened(t0, b"other"),
               lambda: v.set_rejected(t0, "x"),
               lambda: v.set_opened(t1, b"y")):
        with pytest.raises(VaultError):
            fn()
    assert v.pending_count() == 1
    v.close()
    # restart: counters, statuses and payloads all come back from disk
    v2 = SegmentVault(path)
    assert len(v2) == 3 and v2.pending_count() == 1
    assert v2.get(t0)["plaintext"] == b"plain"
    assert v2.get(t1)["status"] == "rejected"
    assert v2.get(t2)["status"] == "pending"
    assert v2.pending_rounds() == [11]
    v2.close()


def test_open_vault_backend_selection(tmp_path, monkeypatch):
    monkeypatch.delenv("DRAND_TPU_TIMELOCK_STORE", raising=False)
    v = open_vault(str(tmp_path / "a.db"))
    assert isinstance(v, TimelockVault)
    v.close()
    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "segment")
    v = open_vault(str(tmp_path / "seg"))
    assert isinstance(v, SegmentVault)
    v.close()
    # an existing segment dir keeps opening as one WITHOUT the env var
    # (a restarted daemon must not silently start a fresh SQLite vault)
    monkeypatch.delenv("DRAND_TPU_TIMELOCK_STORE", raising=False)
    v = open_vault(str(tmp_path / "seg"))
    assert isinstance(v, SegmentVault)
    v.close()
    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "bogus")
    with pytest.raises(VaultError, match="DRAND_TPU_TIMELOCK_STORE"):
        open_vault(str(tmp_path / "b.db"))


# ----------------------------------------------------- CLI migration

def test_cli_migration_equivalence_both_directions(tmp_path):
    """`util store-migrate --vault` round-trips SQLite -> segment ->
    SQLite with every record equal, through the real CLI (verified-copy
    output included)."""
    folder = tmp_path / "node"
    (folder / "db").mkdir(parents=True)
    src = TimelockVault(str(folder / "db" / "timelock.db"))
    rows = ([_row(i, 5 + i % 3) for i in range(30)]
            + [_row(i, 5 + i % 3, "opened") for i in range(30, 40)]
            + [_row(i, 5, "rejected") for i in range(40, 44)])
    src.put_rows(rows)
    assert len(src) == 44 and src.pending_count() == 30
    src.close()

    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""))
    fwd = subprocess.run(
        [sys.executable, "-m", "drand_tpu.cli", "util", "store-migrate",
         "--vault", "--folder", str(folder)],
        env=env, capture_output=True, text=True, timeout=120)
    assert fwd.returncode == 0, fwd.stderr
    out = json.loads(fwd.stdout)
    assert out["migrated"] == 44 and out["pending"] == 30
    assert out["direction"] == "sqlite->segment"

    back_db = str(folder / "db" / "back.db")
    rev = subprocess.run(
        [sys.executable, "-m", "drand_tpu.cli", "util", "store-migrate",
         "--vault", "--reverse", "--db", back_db,
         "-o", str(folder / "db" / "timelock-segments")],
        env=env, capture_output=True, text=True, timeout=120)
    assert rev.returncode == 0, rev.stderr
    assert json.loads(rev.stdout)["migrated"] == 44

    # full-record equivalence keyed by id (row ORDER differs by
    # design: sqlite rows() is insertion-ordered, segment rows() is
    # (round, submitted, token)-ordered)
    a = TimelockVault(str(folder / "db" / "timelock.db"))
    b = TimelockVault(back_db)
    ra = {r["id"]: r for r in a.rows()}
    rb = {r["id"]: r for r in b.rows()}
    assert set(ra) == set(rb) and len(ra) == 44
    for token, x in ra.items():
        y = rb[token]
        for k in ("round", "status", "envelope", "error",
                  "submitted", "opened"):
            assert x[k] == y[k], (token, k)
        pa, pb = x["plaintext"], y["plaintext"]
        assert ((bytes(pa) if pa else None)
                == (bytes(pb) if pb else None)), token
    a.close()
    b.close()
    # typo'd source paths must not auto-create an empty store
    bad = subprocess.run(
        [sys.executable, "-m", "drand_tpu.cli", "util", "store-migrate",
         "--vault", "--db", str(folder / "db" / "absent.db")],
        env=env, capture_output=True, text=True, timeout=120)
    assert bad.returncode != 0
    assert "no timelock db" in bad.stderr
    # a RE-RUN onto the now non-empty destination is refused in BOTH
    # directions: segment put_rows has no duplicate check, so an
    # append would double every row — and open_vault auto-selects the
    # corrupted segment dir on the next daemon start
    for extra in ([],  # forward onto the populated segment dir
                  ["--reverse", "--db", back_db,
                   "-o", str(folder / "db" / "timelock-segments")]):
        rerun = subprocess.run(
            [sys.executable, "-m", "drand_tpu.cli", "util",
             "store-migrate", "--vault", "--folder", str(folder)]
            + extra, env=env, capture_output=True, text=True,
            timeout=120)
        assert rerun.returncode != 0, extra
        assert "already holds" in rerun.stderr, rerun.stderr
    # ...and the refusal left the destination untouched
    check = TimelockVault(back_db)
    assert len(check) == 44
    check.close()


# -------------------------------------------------- O(1) at depth

def test_status_and_pending_count_depth_independent(tmp_path):
    """status() and pending_count() cost on the segment backend must
    not scale with vault depth: a 25x-deeper vault answers within a
    generous constant factor of the shallow one (timer noise on the
    1-core box is real — min-of-repeats and an 8x ceiling keep this
    solid while still failing any O(rows) scan, which would be ~25x)."""
    def build(n: int) -> SegmentVault:
        v = SegmentVault(str(tmp_path / f"seg{n}"))
        v.put_rows((_row(i, 5 + i % 7) for i in range(n)), size_hint=n)
        return v

    def cost(fn, repeats: int = 5) -> float:
        best = float("inf")
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(20):
                fn()
            best = min(best, time.perf_counter() - t0)
        return best

    small, big = build(2_000), build(50_000)
    try:
        assert small.pending_count() == 2_000
        assert big.pending_count() == 50_000
        probe_s = [_tok(i) for i in (1, 999, 1999)]
        probe_b = [_tok(i) for i in (1, 25_000, 49_999)]
        # warm (first touch pays fd open + mmap)
        for v, probes in ((small, probe_s), (big, probe_b)):
            for t in probes:
                assert v.get(t, False)["status"] == "pending"
        c_small = cost(lambda: [small.get(t, False) for t in probe_s])
        c_big = cost(lambda: [big.get(t, False) for t in probe_b])
        assert c_big < c_small * 8, (c_small, c_big)
        p_small = cost(small.pending_count)
        p_big = cost(big.pending_count)
        assert p_big < p_small * 8, (p_small, p_big)
    finally:
        small.close()
        big.close()


# ---------------------------------------- chunked opens + crash resume

@pytest.mark.asyncio
async def test_chunked_open_dispatch_count_and_crash_resume(
        tmp_path, monkeypatch):
    """K=6 ciphertexts at chunk=2 open in exactly ceil(6/2)=3 dispatches
    with a vault commit per chunk; a dispatch CRASH mid-sweep leaves the
    earlier chunks decided, and the restart sweep opens every remaining
    ciphertext exactly once — plaintexts bit-identical to the per-item
    host oracle throughout."""
    from drand_tpu.timelock import TimelockService

    monkeypatch.setenv("DRAND_TPU_TIMELOCK_OPEN_CHUNK", "2")
    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "segment")
    chain = FakeChain(head=1)
    svc = TimelockService(open_vault(str(tmp_path / "seg")), chain)
    await svc.start()
    secrets = [b"secret-%d" % i for i in range(6)]
    tokens = []
    for s in secrets:
        rec = await svc.submit(client_timelock.encrypt_to_round(
            INFO, 4, s))
        tokens.append(rec["id"])
    assert len(set(tokens)) == 6

    # crash the SECOND dispatch: chunk 0 commits, the rest stay pending
    calls = {"n": 0}
    real = batch.decrypt_round_batch

    def crashing(sig, cts, chunk=None):
        calls["n"] += 1
        if calls["n"] == 2:
            raise RuntimeError("injected mid-sweep crash")
        return real(sig, cts, chunk)

    monkeypatch.setattr(batch, "decrypt_round_batch", crashing)
    d0 = metrics.TIMELOCK_OPEN_DISPATCHES._value.get()
    chain.head = 4
    svc.on_result(await chain.get(4))
    for _ in range(200):
        await asyncio.sleep(0.02)
        if calls["n"] >= 2 and not svc._tasks:
            break
    decided = [t for t in tokens
               if (await svc.status(t))["status"] != "pending"]
    assert len(decided) == 2  # exactly chunk 0's commit survived
    # the meter counts COMPLETED dispatches: chunk 0 only (the crash
    # aborted dispatch 2 before its increment)
    assert metrics.TIMELOCK_OPEN_DISPATCHES._value.get() - d0 == 1
    first_opened = {t: (await svc.status(t))["opened"] for t in decided}

    # "restart": a fresh service over the same directory resumes from
    # the last committed chunk — ceil(4/2)=2 more dispatches, nothing
    # re-opened
    monkeypatch.setattr(batch, "decrypt_round_batch", real)
    await svc.close()
    svc = TimelockService(open_vault(str(tmp_path / "seg")), chain)
    d1 = metrics.TIMELOCK_OPEN_DISPATCHES._value.get()
    await svc.start()  # the catch-up sweep drains the remainder
    for _ in range(300):
        await asyncio.sleep(0.02)
        recs = [await svc.status(t) for t in tokens]
        if all(r["status"] != "pending" for r in recs):
            break
    assert all(r["status"] == "opened" for r in recs)
    assert metrics.TIMELOCK_OPEN_DISPATCHES._value.get() - d1 == 2
    for t, s in zip(tokens, secrets):
        rec = await svc.status(t)
        assert base64.b64decode(rec["plaintext"]) == s
    # exactly-once: the crash-surviving rows kept their ORIGINAL
    # decide timestamps (immutable rows were not re-finished)
    for t, ts in first_opened.items():
        assert (await svc.status(t))["opened"] == ts
    await svc.close()


# -------------------------------------------------- partitioned sweeps

@pytest.mark.asyncio
async def test_partitioned_two_worker_sweep_disjoint(
        tmp_path, monkeypatch):
    """Two services sharing ONE segment directory, each with its own
    writer id and token-range shard, drain a round together: every
    ciphertext opens exactly once, each worker decides only ITS shard,
    and the two writers' appends never interleave (disjoint per-writer
    files by construction — asserted via the out_writer on each row)."""
    from drand_tpu.timelock import TimelockService

    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "segment")
    path = str(tmp_path / "seg")
    chain = FakeChain(head=1)
    v0 = SegmentVault(path, writer_id=0)
    v1 = SegmentVault(path, writer_id=1)
    svc0 = TimelockService(v0, chain, shard=(0, 2))
    svc1 = TimelockService(v1, chain, shard=(1, 2))
    await svc0.start()
    await svc1.start()
    assert metrics.TIMELOCK_SWEEP_SHARDS._value.get() == 2

    secrets = {}
    for i in range(10):
        s = b"shard-secret-%d" % i
        rec = await svc0.submit(client_timelock.encrypt_to_round(
            INFO, 6, s))
        secrets[rec["id"]] = s
    by_shard = {0: [], 1: []}
    for t in secrets:
        by_shard[0 if token_in_shard(t, 0, 2) else 1].append(t)
    assert by_shard[0] and by_shard[1], "degenerate token split"

    chain.head = 6
    r = await chain.get(6)
    svc0.on_result(r)
    svc1.on_result(r)
    for _ in range(300):
        await asyncio.sleep(0.02)
        recs = {t: await svc0.status(t) for t in secrets}
        if all(x["status"] != "pending" for x in recs.values()):
            break
    assert all(x["status"] == "opened" for x in recs.values())
    for t, s in secrets.items():
        assert base64.b64decode(recs[t]["plaintext"]) == s
    assert v0.pending_count() == 0
    # provenance: each row's outcome was appended by its shard owner —
    # the workers never wrote into each other's slice
    for t in secrets:
        rec = v1.get(t)  # either handle reads the shared directory
        assert rec["status"] == "opened"
    for shard_idx, toks in by_shard.items():
        for t in toks:
            raw = segvault._raw_token(t)
            locs = (v0 if shard_idx == 0 else v1)._locate(raw)
            assert locs, t
            out_writers = {e[1] for _, _, _, e, _ in locs
                           if e[0] != segvault._S_PENDING}
            assert out_writers == {shard_idx}, (t, out_writers)
    await svc0.close()
    await svc1.close()


# ------------------------------------------------------ open-notify

@pytest.mark.asyncio
async def test_open_notify_sse_delivery_and_snapshot(tmp_path,
                                                     monkeypatch):
    """A token-keyed `GET /timelock?id=` watcher gets exactly one SSE
    frame when its ciphertext's chunk commits (then the stream ends); a
    firehose watcher sees every decided ciphertext; a LATE watcher on
    an already-decided token gets an immediate snapshot frame."""
    from aiohttp.test_utils import TestClient, TestServer

    from drand_tpu.http_server.server import PublicServer
    from drand_tpu.timelock import TimelockService

    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "segment")
    chain = FakeChain(head=1)
    svc = TimelockService(open_vault(str(tmp_path / "seg")), chain)
    server = PublicServer(chain, INFO, timelock_service=svc)
    tc = TestClient(TestServer(server.app))
    await tc.start_server()
    try:
        ids = []
        for i in range(2):
            resp = await tc.post("/timelock", json=(
                client_timelock.encrypt_to_round(INFO, 5,
                                                 b"notify-%d" % i)))
            assert resp.status == 202
            ids.append((await resp.json())["id"])

        async def read_events(path: str, n: int) -> list[dict]:
            events, raw = [], b""
            async with tc.get(path, headers={
                    "Accept": "text/event-stream"}) as r:
                assert r.status == 200
                async for chunk in r.content.iter_any():
                    raw += chunk
                    while b"\n\n" in raw:
                        frame, raw = raw.split(b"\n\n", 1)
                        data = frame.split(b"data: ", 1)[1]
                        events.append(json.loads(data))
                    if len(events) >= n:
                        return events
            return events

        keyed = asyncio.create_task(read_events(
            f"/timelock?id={ids[0]}", 1))
        hose = asyncio.create_task(read_events("/timelock", 2))
        for _ in range(100):
            await asyncio.sleep(0.01)
            if server._tl_hub.watcher_count() == 2:
                break
        assert server._tl_hub.watcher_count() == 2
        before = sample_count(metrics.HTTP_REGISTRY, "timelock_notify",
                              event="opened")
        chain.head = 5
        svc.on_result(await chain.get(5))
        got = await asyncio.wait_for(keyed, 10)
        assert got == [{"id": ids[0], "status": "opened", "round": 5}]
        hose_got = await asyncio.wait_for(hose, 10)
        assert {e["id"] for e in hose_got} == set(ids)
        assert all(e["status"] == "opened" for e in hose_got)
        assert sample_count(metrics.HTTP_REGISTRY, "timelock_notify",
                            event="opened") == before + 2
        # keyed stream ended after its one frame; late watcher gets a
        # decided snapshot without waiting for any publish
        snap = await asyncio.wait_for(
            read_events(f"/timelock?id={ids[1]}", 1), 10)
        assert snap[0]["status"] == "opened"
        for _ in range(100):
            await asyncio.sleep(0.01)
            if server._tl_hub.watcher_count() == 0:
                break
        assert server._tl_hub.watcher_count() == 0
    finally:
        await tc.close()
        await svc.close()


@pytest.mark.asyncio
async def test_watch_poll_fallback_when_open_commits_elsewhere(
        tmp_path, monkeypatch):
    """Multi-worker delivery: a keyed `GET /timelock?id=` watcher whose
    connection landed on a NON-opening worker (here a
    timelock_sweep=False server sharing the vault directory with a
    separate sweeper service — the shared-port relay topology in one
    process) is notified through the shared-vault poll backstop; the
    local hub never publishes, and the stream still ends with the
    decided event instead of hanging forever."""
    from aiohttp.test_utils import TestClient, TestServer

    from drand_tpu.http_server.server import PublicServer
    from drand_tpu.timelock import TimelockService

    monkeypatch.setenv("DRAND_TPU_TIMELOCK_STORE", "segment")
    monkeypatch.setenv("DRAND_TPU_TIMELOCK_WATCH_POLL", "0.05")
    path = str(tmp_path / "seg")
    chain = FakeChain(head=1)
    # the worker the connection lands on: serves the vault, never sweeps
    serve_svc = TimelockService(SegmentVault(path, writer_id=1), chain)
    server = PublicServer(chain, timelock_service=serve_svc,
                          timelock_sweep=False)
    # the worker that owns the open (a separate process in production)
    sweeper = TimelockService(SegmentVault(path, writer_id=0), chain)
    tc = TestClient(TestServer(server.app))
    await tc.start_server()
    try:
        resp = await tc.post("/timelock", json=(
            client_timelock.encrypt_to_round(INFO, 5, b"cross-worker")))
        assert resp.status == 202
        token = (await resp.json())["id"]

        async def read_one() -> dict:
            async with tc.get(f"/timelock?id={token}", headers={
                    "Accept": "text/event-stream"}) as r:
                assert r.status == 200
                raw = b""
                async for chunk in r.content.iter_any():
                    raw += chunk
                    if b"\n\n" in raw:
                        frame = raw.split(b"\n\n", 1)[0]
                        return json.loads(
                            frame.split(b"data: ", 1)[1])
            raise AssertionError("stream ended without an event")

        watcher = asyncio.create_task(read_one())
        for _ in range(100):
            await asyncio.sleep(0.01)
            if server._tl_hub.watcher_count() == 1:
                break
        assert server._tl_hub.watcher_count() == 1
        chain.head = 5
        sweeper.on_result(await chain.get(5))
        got = await asyncio.wait_for(watcher, 10)
        assert got == {"id": token, "status": "opened", "round": 5}
        # delivery came from the shared-vault poll: this worker's hub
        # never published a single event
        assert server._tl_hub.publishes == 0
    finally:
        await tc.close()
        await serve_svc.close()
        await sweeper.close()


def test_opens_locally_matches_shard_membership():
    """opens_locally — the watch handler's is-the-open-mine predicate —
    agrees with the vault-side shard filter for every sampled token,
    and is unconditionally True without a shard."""
    from drand_tpu.timelock import TimelockService

    chain = FakeChain()
    whole = TimelockService(TimelockVault(":memory:"), chain)
    sharded = TimelockService(TimelockVault(":memory:"), chain,
                              shard=(0, 2))
    for i in range(32):
        t = _tok(i)
        assert whole.opens_locally(t) is True
        assert sharded.opens_locally(t) == token_in_shard(t, 0, 2)
    whole._vault.close()
    sharded._vault.close()


def test_open_chunk_env_semantics(monkeypatch):
    """Unset and set-but-EMPTY both select the bounded 2048 default
    (clearing the var means 'reset', not 'unbounded'); only an
    explicit 0 is the monolithic-open escape hatch."""
    from drand_tpu.timelock import TimelockService

    chain = FakeChain()
    for val, want in ((None, 2048), ("", 2048), ("0", 0), ("512", 512)):
        if val is None:
            monkeypatch.delenv("DRAND_TPU_TIMELOCK_OPEN_CHUNK",
                               raising=False)
        else:
            monkeypatch.setenv("DRAND_TPU_TIMELOCK_OPEN_CHUNK", val)
        svc = TimelockService(TimelockVault(":memory:"), chain)
        assert svc._open_chunk == want, (val, svc._open_chunk)
        svc._vault.close()


def test_open_notify_hub_sheds_slow_consumers():
    """A firehose subscriber whose queue is full when a chunk commits
    is disconnected and counted — bounded queues, never unbounded
    buffering (the FanoutHub discipline on the timelock leg)."""
    hub = fanout.TimelockNotifyHub(queue_max=1)
    slow = hub.subscribe(fanout.PROTO_SSE)
    keyed = hub.subscribe(fanout.PROTO_SSE, token=_tok(1))
    assert hub.watcher_count() == 2
    before = sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                          reason="timelock_slow")
    events = [(_tok(i), "opened", 7) for i in range(3)]
    hub.publish_open(events)
    assert slow.shed is True
    assert sample_count(metrics.HTTP_REGISTRY, "relay_shed",
                        reason="timelock_slow") == before + 1
    # the keyed watcher (token _tok(1), queue depth 1, one matching
    # event) survives and got its frame
    assert keyed.shed is False
    assert hub.watcher_count() == 1
    assert metrics.TIMELOCK_WATCHERS._value.get() == 1
    hub.close_all()
    assert metrics.TIMELOCK_WATCHERS._value.get() == 0


# ------------------------------------------------------- /public/span

@pytest.mark.asyncio
async def test_public_span_endpoint_and_client_paging():
    """GET /public/span serves capped, round-echo-validated windows
    (immutable-cacheable only when FULL); HTTPClient.get_span pages
    across the cap and refuses short or misaligned spans."""
    from aiohttp.test_utils import TestClient, TestServer

    from drand_tpu.client.http import HTTPClient
    from drand_tpu.http_server.server import PublicServer

    chain = FakeChain(head=5)
    server = PublicServer(chain, INFO)
    tc = TestClient(TestServer(server.app))
    await tc.start_server()
    try:
        resp = await tc.get("/public/span?from=2&count=3")
        assert resp.status == 200
        body = await resp.json()
        assert body["from"] == 2 and body["count"] == 3
        assert [b["round"] for b in body["beacons"]] == [2, 3, 4]
        assert "immutable" in resp.headers["Cache-Control"]
        assert resp.headers["ETag"] == '"span-2-3"'
        # a PARTIAL prefix (head in the window) must not be cached
        resp = await tc.get("/public/span?from=4&count=10")
        body = await resp.json()
        assert resp.status == 200 and body["count"] == 2
        assert "no-store" in resp.headers["Cache-Control"]
        # nothing available / malformed queries
        assert (await tc.get("/public/span?from=9&count=3")).status == 404
        for q in ("from=0&count=3", "from=1&count=0",
                  "from=x&count=1", "count=1"):
            assert (await tc.get("/public/span?" + q)).status == 400, q
        # server-side cap bounds any one response
        server._span_cap = 2
        body = await (await tc.get("/public/span?from=1&count=5")).json()
        assert body["count"] == 2

        hc = HTTPClient(str(tc.make_url("")))
        try:
            beacons = await hc.get_span(1, 6)  # pages across cap 2
            assert [b.round for b in beacons] == [1, 2, 3, 4, 5]
            assert beacons[2].signature_v2 == _result(3).signature_v2
            with pytest.raises(ClientError):
                await hc.get_span(4, 9)  # short span = no silent prefix
        finally:
            await hc.close()

        # a server echoing the WRONG rounds is refused client-side
        async def lying(path):
            return {"beacons": [{"round": 7, "signature": "",
                                 "previous_signature": "",
                                 "signature_v2": "", "randomness": ""}]}

        hc2 = HTTPClient("http://unused.invalid")
        hc2._get_json = lying
        try:
            with pytest.raises(ClientError, match="carried round"):
                await hc2.get_span(3, 4)
        finally:
            await hc2.close()
    finally:
        await tc.close()
