"""Pedersen DKG + resharing protocol tests (no network, LocalBoard).

Mirrors the reference's DKG coverage driven through core/drand_control.go
(runDKG :123, runResharing :196) and kyber's pedersen dkg semantics:
fresh key generation, fault tolerance (missing dealer), complaint +
justification flow, and key-preserving resharing to a larger group.
"""

import asyncio

import pytest

from drand_tpu.crypto import bls, tbls
from drand_tpu.crypto.curves import PointG1
from drand_tpu.crypto.poly import PubPoly, PriShare
from drand_tpu.dkg import DKGConfig, DKGError, DKGProtocol, LocalBoard
from drand_tpu.key.keys import Node, new_key_pair
from drand_tpu.utils.clock import FakeClock


def make_nodes(n, prefix="dkg-node", start=0):
    pairs = [new_key_pair(f"{prefix}-{i}.test:9{i:03d}", seed=b"%s%d" % (prefix.encode(), i))
             for i in range(start, start + n)]
    nodes = [Node(identity=p.public, index=i) for i, p in enumerate(pairs)]
    return pairs, nodes


async def run_dkg(configs, boards):
    protos = [DKGProtocol(c, b) for c, b in zip(configs, boards)]
    return await asyncio.gather(*(p.run() for p in protos))


def check_group_consistency(results, threshold, expected_key=None):
    """All nodes agree on commits; shares verify against the public poly;
    a threshold of shares produces valid BLS signatures."""
    commits0 = results[0].commits
    for r in results:
        assert [c.to_bytes() for c in r.commits] == \
            [c.to_bytes() for c in commits0]
        assert len(r.commits) == threshold
    if expected_key is not None:
        assert commits0[0] == expected_key
    pub = PubPoly(list(commits0))
    holders = [r for r in results if r.pri_share is not None]
    for r in holders:
        assert PointG1.generator().mul(r.pri_share.value) == \
            pub.eval(r.pri_share.index).value
    # threshold signing works
    msg = b"post-dkg-round"
    partials = [tbls.sign_partial(r.pri_share, msg)
                for r in holders[:threshold]]
    sig = tbls.recover(pub, msg, partials, threshold, len(holders))
    assert tbls.verify_recovered(pub.commit(), msg, sig)
    return pub


@pytest.mark.asyncio
async def test_fresh_dkg_full_participation():
    n, t = 6, 4
    pairs, nodes = make_nodes(n)
    clock = FakeClock()
    boards = LocalBoard.make_group(n)
    configs = [
        DKGConfig(longterm=pairs[i], nonce=b"nonce-1", new_nodes=nodes,
                  threshold=t, clock=clock, seed=b"determinism")
        for i in range(n)
    ]
    results = await run_dkg(configs, boards)
    for r in results:
        assert r.qual == [0, 1, 2, 3, 4, 5]
    check_group_consistency(results, t)


@pytest.mark.asyncio
async def test_dkg_with_crashed_dealer():
    """One node never participates: phases time out, QUAL shrinks to n-1,
    the key still forms (the protocol tolerates n-t crashes)."""
    n, t = 5, 3
    pairs, nodes = make_nodes(n)
    clock = FakeClock()
    boards = LocalBoard.make_group(n)
    configs = [
        DKGConfig(longterm=pairs[i], nonce=b"nonce-2", new_nodes=nodes,
                  threshold=t, clock=clock, phase_timeout=10,
                  seed=b"crashed-dealer")
        for i in range(n - 1)  # node 4 never runs
    ]

    async def drive_clock():
        for _ in range(8):
            await clock.advance(10)

    results_task = asyncio.gather(*(DKGProtocol(c, b).run()
                                    for c, b in zip(configs, boards[:n - 1])))
    await asyncio.gather(results_task, drive_clock())
    results = results_task.result()
    for r in results:
        assert r.qual == [0, 1, 2, 3]
    check_group_consistency(results, t)


@pytest.mark.asyncio
async def test_reshare_preserves_key_and_grows_group():
    """6->9 nodes, threshold 4->5: the distributed key is unchanged, new
    shares verify under the new commits, and old beacons remain valid."""
    n_old, t_old = 6, 4
    pairs_old, nodes_old = make_nodes(n_old)
    clock = FakeClock()
    boards = LocalBoard.make_group(n_old)
    configs = [
        DKGConfig(longterm=pairs_old[i], nonce=b"nonce-3", new_nodes=nodes_old,
                  threshold=t_old, clock=clock, seed=b"reshare-base")
        for i in range(n_old)
    ]
    results = await run_dkg(configs, boards)
    group_key = results[0].commits[0]

    # new group: the 6 old members plus 3 fresh ones, re-indexed 0..8
    pairs_new3, _ = make_nodes(3, prefix="joiner")
    all_pairs = pairs_old + pairs_new3
    new_nodes = [Node(identity=p.public, index=i)
                 for i, p in enumerate(all_pairs)]
    n_new, t_new = 9, 5

    boards2 = LocalBoard.make_group(n_new)
    configs2 = []
    for i, p in enumerate(all_pairs):
        old_share = results[i].pri_share if i < n_old else None
        configs2.append(DKGConfig(
            longterm=p, nonce=b"nonce-4", new_nodes=new_nodes,
            threshold=t_new, old_nodes=nodes_old,
            public_coeffs=list(results[0].commits), old_threshold=t_old,
            share=old_share, clock=clock, seed=b"reshare-new"))
    results2 = await run_dkg(configs2, boards2)

    pub2 = check_group_consistency(results2, t_new, expected_key=group_key)
    # a signature from OLD shares verifies under the NEW public key
    msg = b"cross-era"
    old_partials = [tbls.sign_partial(results[i].pri_share, msg)
                    for i in range(t_old)]
    old_sig = tbls.recover(PubPoly(list(results[0].commits)), msg,
                           old_partials, t_old, n_old)
    assert bls.verify(pub2.commit(), msg, old_sig)


@pytest.mark.asyncio
async def test_reshare_insufficient_old_dealers_fails():
    n_old, t_old = 4, 3
    pairs_old, nodes_old = make_nodes(n_old)
    clock = FakeClock()
    boards = LocalBoard.make_group(n_old)
    base = await run_dkg([
        DKGConfig(longterm=pairs_old[i], nonce=b"n5", new_nodes=nodes_old,
                  threshold=t_old, clock=clock, seed=b"rs-fail")
        for i in range(n_old)
    ], boards)

    # only 2 old dealers participate in the reshare (< old_threshold 3)
    boards2 = LocalBoard.make_group(n_old)
    configs2 = [
        DKGConfig(longterm=pairs_old[i], nonce=b"n6", new_nodes=nodes_old,
                  threshold=t_old, old_nodes=nodes_old,
                  public_coeffs=list(base[0].commits), old_threshold=t_old,
                  share=base[i].pri_share, clock=clock, phase_timeout=10,
                  seed=b"rs-fail2")
        for i in range(2)
    ]

    async def drive_clock():
        for _ in range(8):
            await clock.advance(10)

    async def expect_failures():
        for c, b in zip(configs2, boards2[:2]):
            with pytest.raises(DKGError):
                await DKGProtocol(c, b).run()

    await asyncio.gather(expect_failures(), drive_clock())


class EvilBoard(LocalBoard):
    """Corrupts the encrypted share for one victim in our deal bundle."""

    def __init__(self, registry, victim_index):
        super().__init__(registry)
        self._victim = victim_index

    async def push_deals(self, bundle):
        from drand_tpu.dkg.packets import Deal, DealBundle

        deals = tuple(
            Deal(d.share_index, b"\x00" * len(d.encrypted_share))
            if d.share_index == self._victim else d
            for d in bundle.deals)
        evil = DealBundle(dealer_index=bundle.dealer_index,
                          commits=bundle.commits, deals=deals,
                          session_id=bundle.session_id,
                          signature=bundle.signature)
        await self._fan("deals", evil)


@pytest.mark.asyncio
async def test_complaint_and_justification_flow():
    """Dealer 0 sends node 2 a garbage ciphertext: node 2 complains, dealer
    0 justifies by revealing the share, and everyone (incl. node 2) still
    finishes with dealer 0 in QUAL."""
    n, t = 4, 3
    pairs, nodes = make_nodes(n)
    clock = FakeClock()
    boards = LocalBoard.make_group(n)
    registry = boards[0]._registry
    evil = EvilBoard(registry, victim_index=2)
    registry[0] = evil  # the evil board replaces node 0 in the fan-out
    all_boards = [evil] + boards[1:]

    configs = [
        DKGConfig(longterm=pairs[i], nonce=b"n7", new_nodes=nodes,
                  threshold=t, clock=clock, phase_timeout=10, seed=b"justify")
        for i in range(n)
    ]

    # the evil bundle is signed over the ORIGINAL deals, so the signature
    # no longer matches: LocalBoard skips verification (the gossip board
    # covers that), which lets us exercise the complaint path itself.
    async def drive_clock():
        for _ in range(10):
            await clock.advance(10)

    results_task = asyncio.gather(*(DKGProtocol(c, b).run()
                                    for c, b in zip(configs, all_boards)))
    await asyncio.gather(results_task, drive_clock())
    results = results_task.result()
    for r in results:
        assert r.qual == [0, 1, 2, 3]
    check_group_consistency(results, t)
