"""Beacon-engine tests: the SURVEY.md §7 minimum end-to-end slice.

An in-process t-of-n network over the in-memory transport with a fake
clock — the TestBeaconSimple / TestBeaconSync analogues
(reference: chain/beacon/node_test.go:372-520).
"""

import asyncio

import pytest

from drand_tpu.chain.beacon import verify_beacon, verify_beacon_v2
from drand_tpu.chain.engine.cache import MAX_PARTIALS_PER_NODE, PartialCache
from drand_tpu.net.packets import PartialBeaconPacket
from drand_tpu.testing.harness import BeaconTestNetwork, synthesize_shares
from drand_tpu.crypto import tbls


def run(coro):
    return asyncio.run(coro)


N, T, PERIOD = 3, 2, 10


class TestBeaconSimple:
    def test_rounds_produced_and_verified(self):
        async def main():
            net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
            await net.start_all()
            await net.advance_to_genesis()
            rounds = 4
            for r in range(1, rounds + 1):
                for i in range(N):
                    await net.wait_round(i, r)
                await net.clock.advance(PERIOD)
            # all nodes converged on the same, verifying chain
            pub = net.group.public_key.key()
            ref_chain = list(net.nodes[0].store.cursor())
            assert ref_chain[0].round == 0  # genesis
            assert ref_chain[-1].round >= rounds
            for b in ref_chain[1:]:
                assert verify_beacon(pub, b), f"round {b.round} V1 invalid"
                assert b.is_v2() and verify_beacon_v2(pub, b), f"round {b.round} V2 invalid"
            # chaining: previous_sig links
            for prev, cur in zip(ref_chain, ref_chain[1:]):
                assert cur.previous_sig == prev.signature
            for node in net.nodes[1:]:
                for b_ref, b in zip(ref_chain, node.store.cursor()):
                    assert b_ref.equal(b), "chains diverged"
            net.stop_all()

        run(main())

    def test_only_threshold_nodes_needed(self):
        async def main():
            net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
            # only start T nodes: chain must still advance
            await net.start_all(indices=list(range(T)))
            await net.advance_to_genesis()
            for r in range(1, 3):
                for i in range(T):
                    await net.wait_round(i, r)
                await net.clock.advance(PERIOD)
            assert net.nodes[0].store.last().round >= 2
            net.stop_all()

        run(main())

    def test_below_threshold_stalls(self):
        async def main():
            net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
            await net.start_all(indices=[0])  # 1 < t nodes
            await net.advance_to_genesis()
            await net.clock.advance(PERIOD)
            await net.clock.advance(PERIOD)
            await asyncio.sleep(0.3)
            assert net.nodes[0].store.last().round == 0  # still at genesis
            net.stop_all()

        run(main())


class TestBeaconSync:
    def test_node_catches_up_after_downtime(self):
        async def main():
            net = BeaconTestNetwork(n=N, t=T, period=PERIOD)
            await net.start_all()
            await net.advance_to_genesis()
            # run 2 rounds with everyone
            for r in range(1, 3):
                for i in range(N):
                    await net.wait_round(i, r)
                await net.clock.advance(PERIOD)
            # partition node 2 (its partials still flow out, incoming blocked)
            addr2 = net.nodes[2].addr
            for other in (0, 1):
                net.network.deny(net.nodes[other].addr, addr2)
                net.network.deny(addr2, net.nodes[other].addr)
            for r in range(3, 5):
                for i in (0, 1):
                    await net.wait_round(i, r)
                await net.clock.advance(PERIOD)
            assert net.nodes[2].store.last().round < net.nodes[0].store.last().round
            # heal the partition; next tick triggers gap-sync
            for other in (0, 1):
                net.network.allow(net.nodes[other].addr, addr2)
                net.network.allow(addr2, net.nodes[other].addr)
            target = net.nodes[0].store.last().round + 1
            await net.clock.advance(PERIOD)
            await net.wait_round(2, target)
            b_behind = net.nodes[2].store.get(3)
            assert b_behind is not None and b_behind.equal(net.nodes[0].store.get(3))
            net.stop_all()

        run(main())


class TestPartialCacheDoS:
    def _packet(self, round_no: int, idx: int, tag: bytes = b"") -> PartialBeaconPacket:
        sig = idx.to_bytes(2, "big") + (tag or round_no.to_bytes(4, "big")) * 24
        return PartialBeaconPacket(
            round=round_no, previous_sig=b"prev%d" % round_no,
            partial_sig=sig[:98].ljust(98, b"\x00"), partial_sig_v2=b"")

    def test_round_window_eviction(self):
        cache = PartialCache()
        for r in range(1, 6):
            cache.append(self._packet(r, idx=1))
        assert len(cache.rounds) == 5
        cache.flush_rounds(3)
        assert all(c.round > 3 for c in cache.rounds.values())

    def test_per_node_bound(self):
        cache = PartialCache()
        # node index 7 floods many distinct rounds
        for r in range(1, MAX_PARTIALS_PER_NODE + 50):
            cache.append(self._packet(r, idx=7))
        assert len(cache.rcvd[7]) <= MAX_PARTIALS_PER_NODE
        # oldest entries were evicted
        assert cache.get_round_cache(1, b"prev1") is None

    def test_duplicate_partial_ignored(self):
        cache = PartialCache()
        p = self._packet(1, idx=3)
        cache.append(p)
        cache.append(p)
        rc = cache.get_round_cache(1, b"prev1")
        assert len(rc) == 1


class TestShareSynthesis:
    def test_partials_recover(self):
        shares, dist = synthesize_shares(5, 3, seed=b"x")
        msg = b"some round message"
        partials = [tbls.sign_partial(s.pri_share, msg) for s in shares[:3]]
        sig = tbls.recover(shares[0].pub_poly(), msg, partials, 3, 5)
        assert tbls.verify_recovered(dist.key(), msg, sig)
